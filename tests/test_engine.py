"""Engine-slice tests: command surface -> reply bytes, through the Respond
seam (no sockets), exactly how the reference tests drive Database.apply with
a fake Respond (test/test_cluster.pony:6-41, SURVEY.md section 4).

Covers every repo's command surface (which the reference's own tests do
NOT — SURVEY.md section 4 "what is not tested"), the help/error texts, the
proactive-flush throttle, and two-node delta convergence through
flush_deltas -> converge_deltas.
"""

import numpy as np
import pytest

import jylis_tpu  # noqa: F401
from jylis_tpu.models import Database
from jylis_tpu.server.resp import Respond


class Out:
    """Byte-collecting Respond sink (the reference's _ExpectRespond seam)."""

    def __init__(self):
        self.buf = bytearray()

    def sink(self, data: bytes):
        self.buf += data

    def take(self) -> bytes:
        out = bytes(self.buf)
        self.buf.clear()
        return out


@pytest.fixture()
def db():
    return Database(identity=1)


def run(db, *words) -> bytes:
    out = Out()
    db.apply(Respond(out.sink), [w.encode() if isinstance(w, str) else w for w in words])
    return out.take()


# -- GCOUNT ----------------------------------------------------------------


def test_gcount_inc_get(db):
    assert run(db, "GCOUNT", "GET", "k") == b":0\r\n"
    assert run(db, "GCOUNT", "INC", "k", "10") == b"+OK\r\n"
    assert run(db, "GCOUNT", "GET", "k") == b":10\r\n"
    assert run(db, "GCOUNT", "INC", "k", "15") == b"+OK\r\n"
    assert run(db, "GCOUNT", "GET", "k") == b":25\r\n"


def test_gcount_bad_value_gets_help(db):
    got = run(db, "GCOUNT", "INC", "k", "abc")
    assert got.startswith(b"-BADCOMMAND (could not parse command)\n")
    assert b"GCOUNT INC key value" in got


# -- PNCOUNT ---------------------------------------------------------------


def test_pncount_inc_dec(db):
    assert run(db, "PNCOUNT", "GET", "k") == b":0\r\n"
    run(db, "PNCOUNT", "INC", "k", "10")
    run(db, "PNCOUNT", "DEC", "k", "15")
    assert run(db, "PNCOUNT", "GET", "k") == b":-5\r\n"


# -- TREG ------------------------------------------------------------------


def test_treg_set_get(db):
    assert run(db, "TREG", "GET", "mykey") == b"$-1\r\n"
    assert run(db, "TREG", "SET", "mykey", "hello", "10") == b"+OK\r\n"
    assert run(db, "TREG", "GET", "mykey") == b"*2\r\n$5\r\nhello\r\n:10\r\n"
    run(db, "TREG", "SET", "mykey", "world", "15")
    assert run(db, "TREG", "GET", "mykey") == b"*2\r\n$5\r\nworld\r\n:15\r\n"
    run(db, "TREG", "SET", "mykey", "outdated", "5")
    assert run(db, "TREG", "GET", "mykey") == b"*2\r\n$5\r\nworld\r\n:15\r\n"


# -- TLOG ------------------------------------------------------------------


def test_tlog_surface(db):
    assert run(db, "TLOG", "GET", "chat") == b"*0\r\n"
    run(db, "TLOG", "INS", "chat", "one", "100")
    run(db, "TLOG", "INS", "chat", "two", "200")
    run(db, "TLOG", "INS", "chat", "three", "150")
    assert run(db, "TLOG", "SIZE", "chat") == b":3\r\n"
    got = run(db, "TLOG", "GET", "chat")
    assert got == (
        b"*3\r\n"
        b"*2\r\n$3\r\ntwo\r\n:200\r\n"
        b"*2\r\n$5\r\nthree\r\n:150\r\n"
        b"*2\r\n$3\r\none\r\n:100\r\n"
    )
    assert run(db, "TLOG", "GET", "chat", "1") == b"*1\r\n*2\r\n$3\r\ntwo\r\n:200\r\n"
    # unparseable count means "all" (reference quirk, repo_tlog.pony:49-50)
    assert run(db, "TLOG", "GET", "chat", "zzz").startswith(b"*3\r\n")
    run(db, "TLOG", "TRIM", "chat", "2")
    assert run(db, "TLOG", "SIZE", "chat") == b":2\r\n"
    assert run(db, "TLOG", "CUTOFF", "chat") == b":150\r\n"
    run(db, "TLOG", "TRIMAT", "chat", "200")
    assert run(db, "TLOG", "SIZE", "chat") == b":1\r\n"
    run(db, "TLOG", "CLR", "chat")
    assert run(db, "TLOG", "SIZE", "chat") == b":0\r\n"
    assert run(db, "TLOG", "CUTOFF", "chat") == b":201\r\n"
    # re-inserting below cutoff is silently ignored
    assert run(db, "TLOG", "INS", "chat", "old", "100") == b"+OK\r\n"
    assert run(db, "TLOG", "SIZE", "chat") == b":0\r\n"


def test_treg_reads_never_touch_device(db, monkeypatch):
    """TREG GET computes the LWW winner from the host cache + pending
    coalesce — ZERO device calls even right after writes and converges,
    and the answer matches the post-drain truth."""
    from jylis_tpu.models import repo_treg

    run(db, "TREG", "SET", "m", "alpha", "5")
    repo = db.manager("TREG").repo
    repo.converge(b"m", (b"zeta", 5))  # ts tie: larger value wins

    calls = {"n": 0}
    for name in ("_drain", "_drain_dense", "_patch_vids"):
        monkeypatch.setattr(
            repo_treg, name,
            lambda *a, **k: calls.__setitem__("n", calls["n"] + 1),
        )
    monkeypatch.setattr(
        type(repo), "_drain_sharded",
        lambda *a: calls.__setitem__("n", calls["n"] + 1),
    )
    assert run(db, "TREG", "GET", "m") == b"*2\r\n$4\r\nzeta\r\n:5\r\n"
    assert run(db, "TREG", "GET", "nope") == b"$-1\r\n"
    assert calls["n"] == 0
    monkeypatch.undo()
    repo.drain()  # post-drain truth agrees with the host compare
    assert run(db, "TREG", "GET", "m") == b"*2\r\n$4\r\nzeta\r\n:5\r\n"


def test_tlog_reads_never_drain(db, monkeypatch):
    """GET/SIZE/CUTOFF with pending entries serve the exact merged view
    host-side — no drain dispatch; answers equal the post-drain truth
    (union + dedup + cutoff filter, tlog.md:116-133)."""
    from jylis_tpu.models import repo_tlog

    run(db, "TLOG", "INS", "m", "base", "10")
    run(db, "TLOG", "GET", "m")  # drain + render cache for the base
    repo = db.manager("TLOG").repo
    run(db, "TLOG", "INS", "m", "new", "20")
    repo.converge(b"m", ([(b"base", 10), (b"old", 1)], 5))  # dup + cutoff 5

    calls = {"n": 0}
    monkeypatch.setattr(
        repo_tlog, "_drain",
        lambda *a: calls.__setitem__("n", calls["n"] + 1),
    )
    monkeypatch.setattr(
        type(repo), "_drain_sharded",
        lambda *a: calls.__setitem__("n", calls["n"] + 1),
    )
    want = (
        b"*2\r\n*2\r\n$3\r\nnew\r\n:20\r\n*2\r\n$4\r\nbase\r\n:10\r\n"
    )
    assert run(db, "TLOG", "GET", "m") == want  # deduped, desc
    assert run(db, "TLOG", "SIZE", "m") == b":2\r\n"
    assert run(db, "TLOG", "CUTOFF", "m") == b":5\r\n"
    assert calls["n"] == 0
    monkeypatch.undo()
    repo.drain()  # the device agrees with the host merge
    assert run(db, "TLOG", "GET", "m") == want
    assert run(db, "TLOG", "SIZE", "m") == b":2\r\n"
    assert run(db, "TLOG", "CUTOFF", "m") == b":5\r\n"


def test_tlog_quiescent_reads_skip_device(db, monkeypatch):
    """After a drain, repeated GET/SIZE/CUTOFF perform ZERO device calls:
    GET serves from the rendered row cache, SIZE/CUTOFF from the host
    length/cutoff caches (VERDICT r01 weak #3 — the counter repos' host
    shadow pattern applied to TLOG)."""
    from jylis_tpu.models import repo_tlog

    run(db, "TLOG", "INS", "chat", "one", "100")
    run(db, "TLOG", "INS", "chat", "two", "200")
    first = run(db, "TLOG", "GET", "chat")  # drains + builds render cache

    calls = {"get_row": 0, "drain": 0}
    monkeypatch.setattr(
        repo_tlog,
        "_get_row",
        lambda *a: calls.__setitem__("get_row", calls["get_row"] + 1),
    )
    monkeypatch.setattr(
        repo_tlog,
        "_drain",
        lambda *a: calls.__setitem__("drain", calls["drain"] + 1),
    )
    for _ in range(3):
        assert run(db, "TLOG", "GET", "chat") == first
        assert run(db, "TLOG", "SIZE", "chat") == b":2\r\n"
        assert run(db, "TLOG", "CUTOFF", "chat") == b":0\r\n"
        assert run(db, "TLOG", "GET", "missing") == b"*0\r\n"
    assert calls == {"get_row": 0, "drain": 0}


def test_tlog_render_cache_invalidated_by_merge(db):
    """A foreign delta (or local INS) touching the row must be visible on
    the next GET — the cache drops exactly the merged rows."""
    run(db, "TLOG", "INS", "chat", "one", "100")
    assert run(db, "TLOG", "GET", "chat") == b"*1\r\n*2\r\n$3\r\none\r\n:100\r\n"
    mgr = db.manager("TLOG")
    mgr.repo.converge(b"chat", ([(b"two", 200)], 0))
    assert run(db, "TLOG", "GET", "chat") == (
        b"*2\r\n*2\r\n$3\r\ntwo\r\n:200\r\n*2\r\n$3\r\none\r\n:100\r\n"
    )
    # trim also invalidates
    run(db, "TLOG", "TRIM", "chat", "1")
    assert run(db, "TLOG", "GET", "chat") == b"*1\r\n*2\r\n$3\r\ntwo\r\n:200\r\n"


def test_dense_drain_equivalence():
    """A small-capacity repo (batch covers >=1/4 of the keyspace -> dense
    elementwise drain) must serve identical values to a large-capacity one
    (sparse scatter drain) on the same operations."""
    from jylis_tpu.models.repo_counters import RepoGCOUNT, RepoPNCOUNT
    from jylis_tpu.models.repo_treg import RepoTREG

    rng = np.random.default_rng(3)
    keys = [b"k%d" % i for i in range(12)]
    decs = {k: int(rng.integers(1, 4)) for k in keys}

    for cls in (RepoGCOUNT, RepoPNCOUNT):
        small = cls(identity=1, key_cap=16, rep_cap=4)  # dense path
        big = cls(identity=1, key_cap=4096, rep_cap=4)  # sparse path
        for repo in (small, big):
            for k in keys:
                repo.converge(
                    k, {7: 5} if cls is RepoGCOUNT else ({7: 5}, {9: decs[k]})
                )
            repo.drain()
        for k in keys:
            assert small._get_value(k) == big._get_value(k), (cls.__name__, k)

    small = RepoTREG(identity=1, key_cap=16)
    big = RepoTREG(identity=1, key_cap=4096)
    shared = b"longsharedprefix-"  # >8 bytes: rank collision -> device tie
    for repo in (small, big):
        for i, k in enumerate(keys):
            repo.converge(k, (b"v%d" % i, 10 + i))
            # drain between the colliding writes so the tie reaches the
            # device (one-drain writes coalesce host-side first)
            repo.converge(k, (shared + (b"aaa" if i % 2 else b"zzz"), 100))
        repo.drain()
        for i, k in enumerate(keys):
            repo.converge(k, (shared + (b"zzz" if i % 2 else b"aaa"), 100))
        repo.drain()  # tie rows resolve on host: zzz must win either order
    for k in keys:
        srow, brow = small._tbl.find(k), big._tbl.find(k)
        assert small._cache[srow][0] == big._cache[brow][0] == 100
        assert (
            small._interner.lookup(small._cache[srow][1])
            == big._interner.lookup(big._cache[brow][1])
            == shared + b"zzz"
        )


# -- UJSON -----------------------------------------------------------------


def test_ujson_surface(db):
    assert run(db, "UJSON", "GET", "u") == b"$0\r\n\r\n"
    run(db, "UJSON", "SET", "u", '{"a":1,"b":{"c":true}}')
    assert run(db, "UJSON", "GET", "u", "a") == b"$1\r\n1\r\n"
    assert run(db, "UJSON", "GET", "u", "b") == b'$10\r\n{"c":true}\r\n'
    run(db, "UJSON", "INS", "u", "roles", '"admin"')
    run(db, "UJSON", "INS", "u", "roles", '"user"')
    assert run(db, "UJSON", "GET", "u", "roles") == b'$16\r\n["admin","user"]\r\n'
    run(db, "UJSON", "RM", "u", "roles", '"admin"')
    assert run(db, "UJSON", "GET", "u", "roles") == b'$6\r\n"user"\r\n'
    run(db, "UJSON", "CLR", "u", "b")
    assert run(db, "UJSON", "GET", "u", "b") == b"$0\r\n\r\n"
    # invalid JSON -> help
    got = run(db, "UJSON", "SET", "u", "{not json")
    assert got.startswith(b"-BADCOMMAND")


# -- SYSTEM ----------------------------------------------------------------


def test_system_getlog(db):
    db.system.inslog("node started")
    db.system.inslog("something happened")
    got = run(db, "SYSTEM", "GETLOG")
    assert got.startswith(b"*2\r\n")
    assert b"something happened" in got
    got1 = run(db, "SYSTEM", "GETLOG", "1")
    assert got1.startswith(b"*1\r\n")


# -- routing / help --------------------------------------------------------


def test_unknown_type_lists_datatypes(db):
    got = run(db, "NOPE", "GET", "k")
    assert got.startswith(b"-BADCOMMAND (could not parse command)\n")
    for t in (b"TREG", b"TLOG", b"GCOUNT", b"PNCOUNT", b"UJSON", b"SYSTEM"):
        assert t in got


def test_unknown_op_lists_type_ops(db):
    got = run(db, "TREG", "FROB", "k")
    assert b"The following are valid operations for this data type:" in got
    assert b"TREG GET key" in got
    assert b"TREG SET key value timestamp" in got


def test_known_op_bad_args_shows_usage(db):
    got = run(db, "TREG", "SET", "k")
    assert b"This operation expects the arguments in the following form:" in got
    assert b"TREG SET key value timestamp" in got


# -- delta flow ------------------------------------------------------------


def collect_flush(db):
    batches = []
    db.flush_deltas(lambda named: batches.append(named))
    return batches


def test_two_node_convergence_all_types(db):
    """Node A mutates every type; its flushed deltas converge node B to the
    same observable state (the reference's TestCluster assertion, minus the
    wire — that arrives with the cluster layer)."""
    a = db
    b = Database(identity=2)

    run(a, "GCOUNT", "INC", "k", "7")
    run(a, "PNCOUNT", "INC", "k", "10")
    run(a, "PNCOUNT", "DEC", "k", "4")
    run(a, "TREG", "SET", "r", "v1", "9")
    run(a, "TLOG", "INS", "l", "entry", "50")
    run(a, "UJSON", "SET", "u", '{"x":[1,2]}')
    a.system.inslog("hello from a")

    for named in collect_flush(a):
        b.converge_deltas(named)

    assert run(b, "GCOUNT", "GET", "k") == b":7\r\n"
    assert run(b, "PNCOUNT", "GET", "k") == b":6\r\n"
    assert run(b, "TREG", "GET", "r") == b"*2\r\n$2\r\nv1\r\n:9\r\n"
    assert run(b, "TLOG", "GET", "l") == b"*1\r\n*2\r\n$5\r\nentry\r\n:50\r\n"
    assert run(b, "UJSON", "GET", "u") == b'$11\r\n{"x":[1,2]}\r\n'
    assert b"hello from a" in run(b, "SYSTEM", "GETLOG")

    # cross-write: both nodes INC, both converge, both read the same total
    run(b, "GCOUNT", "INC", "k", "3")
    for named in collect_flush(b):
        a.converge_deltas(named)
    assert run(a, "GCOUNT", "GET", "k") == b":10\r\n"


def test_proactive_flush_throttle():
    clock = [100.0]
    db = Database(identity=1)
    mgr = db.manager("GCOUNT")
    mgr._clock = lambda: clock[0]
    sent = []
    db.flush_deltas(lambda named: sent.append(named))
    sent.clear()

    run(db, "GCOUNT", "INC", "k", "1")  # first mutation flushes immediately
    assert len(sent) == 1
    run(db, "GCOUNT", "INC", "k", "1")  # throttled
    assert len(sent) == 1
    clock[0] += 0.6
    run(db, "GCOUNT", "INC", "k", "1")  # past the window: flushes again
    assert len(sent) == 2


def test_shutdown_rejects_commands(db):
    db.clean_shutdown()
    got = run(db, "GCOUNT", "GET", "k")
    assert got.startswith(b"-SHUTDOWN")


def test_many_keys_growth(db):
    """Push past the initial key capacity to exercise state growth."""
    for i in range(100):
        run(db, "GCOUNT", "INC", "key%d" % i, str(i + 1))
    assert run(db, "GCOUNT", "GET", "key99") == b":100\r\n"
    vals = [run(db, "GCOUNT", "GET", "key%d" % i) for i in range(0, 100, 17)]
    assert vals == [b":%d\r\n" % (i + 1) for i in range(0, 100, 17)]


def test_counter_gets_skip_device_when_local_only(db):
    """Read-your-writes host shadow: GETs after purely-local INC/DEC are
    served from the exact host value cache with NO device drain; a foreign
    delta makes exactly the next GET drain."""
    counters = db.metrics.counters  # the per-Database registry's view
    counters.pop("GCOUNT", None)
    for i in range(5):
        run(db, "GCOUNT", "INC", "k", "3")
        assert run(db, "GCOUNT", "GET", "k") == b":%d\r\n" % (3 * (i + 1))
    assert counters["GCOUNT"]["batches"] == 0  # no drains

    mgr = db.manager("GCOUNT")
    mgr.repo.converge(b"k", {999: 100})
    assert run(db, "GCOUNT", "GET", "k") == b":115\r\n"
    assert counters["GCOUNT"]["batches"] == 1  # exactly one drain

    # and PNCOUNT wraps its eager adjust into the signed read domain
    run(db, "PNCOUNT", "DEC", "pk", "5")
    assert run(db, "PNCOUNT", "GET", "pk") == b":-5\r\n"
    # a DEC past the i64 boundary must wrap exactly like the device's
    # modular bitcast read: -(2^63+5) -> 2^63-5
    run(db, "PNCOUNT", "DEC", "pk2", str(2**63 + 5))
    want = b":%d\r\n" % (2**63 - 5)
    assert run(db, "PNCOUNT", "GET", "pk2") == want  # eager host path
    db.manager("PNCOUNT").repo.converge(b"pk2", ({}, {}))  # force a drain
    assert run(db, "PNCOUNT", "GET", "pk2") == want  # device path agrees


def test_system_metrics_command(db):
    """SYSTEM METRICS (extension): live per-type drain counters over
    RESP — drains become visible without waiting for the shutdown
    report."""
    before = int(db.metrics.counters["TLOG"]["batches"])
    run(db, "TLOG", "INS", "m:met", "x", "5")
    db.manager("TLOG").repo.drain()
    out = run(db, "SYSTEM", "METRICS")
    assert out.startswith(b"*")
    assert b"TLOG drains" in out
    # the counter moved past its pre-test value
    lines = [l for l in out.split(b"\r\n") if l.startswith(b"TLOG drains")]
    assert lines, out
    # parse "TLOG drains N" from the bulk payload
    n = int(lines[0].rsplit(b" ", 1)[1])
    assert n >= before + 1
    # unknown op still errors with the (extended) help table
    err = run(db, "SYSTEM", "NOPE")
    assert err.startswith(b"-BADCOMMAND") and b"METRICS" in err


def _metric_value(out: bytes, prefix: bytes) -> int:
    lines = [l for l in out.split(b"\r\n") if l.startswith(prefix)]
    assert lines, out
    return int(lines[0].rsplit(b" ", 1)[1])


def test_system_metrics_counts_served_commands(db):
    """METRICS "cmds" lines (extension): commands served per type,
    counted on BOTH serving paths — Python dispatch (manager._apply_core
    -> the per-Database tally) and the native batch applier
    (Engine::served, merged in via RepoSYSTEM.served_fn)."""
    run(db, "GCOUNT", "INC", "m:srv", "1")
    run(db, "GCOUNT", "GET", "m:srv")
    total = _metric_value(run(db, "SYSTEM", "METRICS"), b"GCOUNT cmds")
    assert total == 2  # per-instance tally: exactly this test's commands
    eng = db.native_engine
    if eng is not None:
        rc, _, replies, _, _ = eng.scan_apply(
            bytearray(b"GCOUNT INC m:srv 1\r\nGCOUNT GET m:srv\r\n")
        )
        assert rc == 0 and replies == b"+OK\r\n:2\r\n"
        assert eng.served_counts()["GCOUNT"] == 2
        assert _metric_value(
            run(db, "SYSTEM", "METRICS"), b"GCOUNT cmds"
        ) == total + 2
    # a second Database sees none of the first's counts (per-instance
    # wiring, unlike the process-global drain counters)
    other = Database(identity=2, engine="python")
    out = run(other, "SYSTEM", "METRICS")
    assert not [
        l for l in out.split(b"\r\n") if l.startswith(b"GCOUNT cmds")
    ], out
    run(other, "GCOUNT", "GET", "m:srv")
    assert _metric_value(run(other, "SYSTEM", "METRICS"), b"GCOUNT cmds") == 1
