"""Differential tests: native cluster codec vs the pure-Python oracle.

Same posture as tests/test_native_resp.py: the Python implementation in
cluster/codec.py is the semantic oracle; the C++ fast path
(native/cluster_codec.cpp via jylis_tpu/native/codec.py) must be
byte-identical on encode and object-equal on decode for every input it
accepts, and must decline (return None -> caller falls back) on anything
outside its domain — including inputs where the oracle raises.
"""

import random

import pytest

from jylis_tpu.cluster import codec
from jylis_tpu.cluster.msg import MsgPushDeltas
from jylis_tpu.native import codec as ncodec
from jylis_tpu.native import lib

pytestmark = pytest.mark.skipif(
    lib() is None, reason="native library unavailable (no C++ toolchain)"
)


def _rand_key(rng: random.Random) -> bytes:
    n = rng.choice([0, 1, 3, 17, 200])
    return bytes(rng.randrange(256) for _ in range(n))


def _rand_u64(rng: random.Random) -> int:
    # bias toward varint length boundaries
    return rng.choice(
        [0, 1, 127, 128, rng.randrange(1 << 21), rng.randrange(1 << 49),
         (1 << 64) - 1, rng.randrange(1 << 64)]
    )


def _rand_gdict(rng: random.Random) -> dict:
    return {rng.randrange(1 << 63): _rand_u64(rng) for _ in range(rng.randrange(6))}


def _rand_str(rng: random.Random) -> str:
    return rng.choice(["", "a", "profile", "über", "名前", "x" * 40])


def _rand_ujson(rng: random.Random):
    from jylis_tpu.ops.ujson_host import UJSON

    u = UJSON()
    for _ in range(rng.randrange(4)):
        dot = (rng.randrange(1 << 63), _rand_u64(rng))
        path = tuple(_rand_str(rng) for _ in range(rng.randrange(3)))
        u.entries[dot] = (path, _rand_str(rng))
    for _ in range(rng.randrange(3)):
        u.ctx.vv[rng.randrange(1 << 63)] = _rand_u64(rng)
    for _ in range(rng.randrange(3)):
        u.ctx.cloud.add((rng.randrange(1 << 63), _rand_u64(rng)))
    return u


def _rand_msg(rng: random.Random, name: str) -> MsgPushDeltas:
    batch = []
    for _ in range(rng.randrange(5)):
        key = _rand_key(rng)
        if name == "GCOUNT":
            delta = _rand_gdict(rng)
        elif name == "PNCOUNT":
            delta = (_rand_gdict(rng), _rand_gdict(rng))
        elif name == "TREG":
            delta = (_rand_key(rng), _rand_u64(rng))
        elif name == "UJSON":
            delta = _rand_ujson(rng)
        else:  # TLOG / SYSTEM
            entries = [
                (_rand_key(rng), _rand_u64(rng))
                for _ in range(rng.randrange(4))
            ]
            delta = (entries, _rand_u64(rng))
        batch.append((key, delta))
    return MsgPushDeltas(name, tuple(batch))


NAMES = ["GCOUNT", "PNCOUNT", "TREG", "TLOG", "SYSTEM", "UJSON"]


@pytest.mark.parametrize("name", NAMES)
def test_encode_byte_identical_to_oracle(name):
    rng = random.Random(f"enc-{name}")
    for _ in range(200):
        msg = _rand_msg(rng, name)
        fast = ncodec.encode_push(msg)
        assert fast is not None, "native encoder declined a valid message"
        assert fast == codec._encode_oracle(msg)


@pytest.mark.parametrize("name", NAMES)
def test_decode_equals_oracle(name):
    rng = random.Random(f"dec-{name}")
    for _ in range(200):
        msg = _rand_msg(rng, name)
        body = codec._encode_oracle(msg)
        fast = ncodec.decode_push(body)
        assert fast is not None, "native decoder declined oracle bytes"
        assert fast == codec._decode_oracle(body) == msg


@pytest.mark.parametrize("name", NAMES)
def test_public_roundtrip_uses_native(name):
    rng = random.Random(f"rt-{name}")
    for _ in range(50):
        msg = _rand_msg(rng, name)
        assert codec.decode(codec.encode(msg)) == msg


def test_mutation_fuzz_never_diverges():
    """Mutated wire bytes: wherever the native decoder accepts, the oracle
    must accept with the identical result; where the oracle raises, the
    native path must have declined (so the public decode still raises)."""
    rng = random.Random("mutate")
    for trial in range(400):
        name = rng.choice(NAMES)
        body = bytearray(codec._encode_oracle(_rand_msg(rng, name)))
        if not body:
            continue
        for _ in range(rng.randrange(1, 4)):
            body[rng.randrange(len(body))] = rng.randrange(256)
        body = bytes(body)
        if not body or body[0] != 3:
            continue  # not a PushDeltas any more; native path not consulted
        try:
            expect = codec._decode_oracle(body)
            oracle_raised = False
        except codec.CodecError:
            oracle_raised = True
        fast = ncodec.decode_push(body)
        if fast is not None:
            assert not oracle_raised, "native accepted bytes the oracle rejects"
            assert fast == expect
        if oracle_raised:
            with pytest.raises(codec.CodecError):
                codec.decode(body)


def test_oversize_values_fall_back_to_oracle():
    """Values outside u64 are out of the native domain on both sides but
    must still roundtrip through the public API via the oracle."""
    big = 1 << 70
    msg = MsgPushDeltas("GCOUNT", ((b"k", {3: big}),))
    assert ncodec.encode_push(msg) is None
    body = codec.encode(msg)
    assert ncodec.decode_push(body) is None  # 65+-bit varint -> decline
    assert codec.decode(body) == msg


def test_empty_batch_and_empty_dicts():
    from jylis_tpu.ops.ujson_host import UJSON

    for msg in [
        MsgPushDeltas("GCOUNT", ()),
        MsgPushDeltas("PNCOUNT", ((b"", ({}, {})),)),
        MsgPushDeltas("TLOG", ((b"k", ([], 0)),)),
        MsgPushDeltas("UJSON", ((b"k", UJSON()),)),
    ]:
        fast = ncodec.encode_push(msg)
        assert fast == codec._encode_oracle(msg)
        assert codec.decode(fast) == msg
