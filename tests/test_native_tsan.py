"""Deliberately jax-free multi-threaded drive of the native serving
engine — the `make sanitize-threads` vehicle.

The TSAN build (`make sanitize-threads`) runs this module with
libtsan LD_PRELOADed. Two invariants are certified:

* **Engine isolation** — distinct ``ServeEngine`` instances carry no
  hidden shared C++ state (statics, shared buffers, a shared
  interner). ctypes releases the GIL around every FFI call, so the
  per-thread bursts below genuinely run concurrently inside the
  library; any cross-engine write TSAN sees is a product bug, because
  the lane supervisor runs one engine per process and the single-node
  server runs one per asyncio loop.
* **External-mutex discipline** — a single engine shared across
  threads is race-free when every call is serialized by one lock
  (the product's implicit contract: the owning event loop is that
  lock). TSAN proves no engine call path touches state that escapes
  the critical section (e.g. an unsynchronized static scratch buffer
  would race even under the mutex between release/acquire pairs).

In the regular suite this doubles as a plain concurrency smoke (the
invariants hold under the GIL too — assertion failures here mean
cross-engine state leaked regardless of the data-race question).

Keep this module importable without jax: no jylis_tpu.models /
jylis_tpu.ops imports (JYLIS_SANITIZE gates the jax import in
tests/conftest.py).
"""

from __future__ import annotations

import threading

import pytest

from jylis_tpu.native import lib
from jylis_tpu.native.engine import ServeEngine

N_THREADS = 6
N_ROUNDS = 40


@pytest.fixture
def cdll():
    c = lib()
    assert c is not None, "native library must build in this environment"
    return c


def resp(*args: bytes) -> bytes:
    out = b"*%d\r\n" % len(args)
    for a in args:
        out += b"$%d\r\n%s\r\n" % (len(a), a)
    return out


def drain_native(eng, burst: bytes):
    """Same drain loop as test_native_drive (tests/ is not a package,
    so the helper is restated rather than imported)."""
    buf = bytearray(burst)
    replies = b""
    deferred = []
    while True:
        rc, consumed, out, unhandled, _changed = eng.scan_apply(buf)
        replies += out
        del buf[:consumed]
        if rc == 1:
            deferred.append(unhandled)
            continue
        if rc == 2:
            continue
        return rc, replies, deferred, bytes(buf)


def _full_surface_burst(tag: bytes, i: int) -> bytes:
    """One burst over all five natively-served types, keys salted by
    thread tag so per-engine results are predictable."""
    k = tag + b"-%d" % (i % 4)
    return (
        resp(b"GCOUNT", b"INC", k, b"3")
        + resp(b"GCOUNT", b"GET", k)
        + resp(b"PNCOUNT", b"INC", k, b"2")
        + resp(b"PNCOUNT", b"DEC", k, b"1")
        + resp(b"TREG", b"SET", k, tag + b"-v%d" % i, b"%d" % (i + 1))
        + resp(b"TREG", b"GET", k)
        + resp(b"TLOG", b"INS", k, b"e%d" % i, b"%d" % (i + 1))
        + resp(b"TLOG", b"SIZE", k)
        + resp(b"UJSON", b"SET", k, b"n", b"%d" % i)
        + resp(b"UJSON", b"CLR", k)
    )


def _run_threads(workers):
    errors: list[BaseException] = []

    def wrap(fn):
        def run():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errors.append(e)

        return run

    threads = [threading.Thread(target=wrap(w)) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]


def test_concurrent_engines_are_isolated(cdll):
    """One engine per thread, full-surface bursts in parallel: every
    thread's replies and drain counts must be exactly what a solo run
    produces — and TSAN must see no cross-engine access."""
    results: dict[bytes, tuple] = {}
    lock = threading.Lock()

    def worker(tag: bytes):
        eng = ServeEngine(cdll)
        replies = b""
        for i in range(N_ROUNDS):
            rc, out, deferred, rest = drain_native(
                eng, _full_surface_burst(tag, i)
            )
            assert (rc, rest) == (0, b"") and not deferred
            replies += out
        summary = (
            replies,
            eng.served_counts(),
            sorted(eng.treg_flush_deltas()),
            len(eng.uq_drain()),
        )
        with lock:
            results[tag] = summary

    _run_threads(
        [lambda t=b"t%d" % n: worker(t) for n in range(N_THREADS)]
    )
    assert len(results) == N_THREADS
    # every engine saw only its own traffic: identical shapes, keys
    # salted by tag, and the reply streams are the solo-run streams
    solo = ServeEngine(cdll)
    expect = b""
    for i in range(N_ROUNDS):
        rc, out, deferred, _ = drain_native(
            solo, _full_surface_burst(b"t0", i)
        )
        assert rc == 0 and not deferred
        expect += out
    assert results[b"t0"][0] == expect
    for tag, (_, served, deltas, uq) in results.items():
        assert served["GCOUNT"] == 2 * N_ROUNDS
        assert {k for k, _ in deltas} == {
            tag + b"-%d" % j for j in range(4)
        }
        assert uq == 2 * N_ROUNDS


def test_shared_engine_under_external_mutex(cdll):
    """One engine, many threads, one lock around every call — the
    product's serialization contract. The final counter state must be
    the arithmetic sum, and TSAN must be silent (no engine code path
    may touch state outside the critical section)."""
    eng = ServeEngine(cdll)
    mu = threading.Lock()

    def worker(n: int):
        for i in range(N_ROUNDS):
            with mu:
                rc, out, deferred, rest = drain_native(
                    eng,
                    resp(b"GCOUNT", b"INC", b"shared", b"1")
                    + resp(b"PNCOUNT", b"INC", b"shared", b"2")
                    + resp(b"PNCOUNT", b"DEC", b"shared", b"1")
                    + resp(b"TLOG", b"INS", b"shared", b"e%d-%d" % (n, i),
                           b"%d" % (n * N_ROUNDS + i + 1)),
                )
                assert (rc, rest) == (0, b"") and not deferred
                assert out.count(b"+OK\r\n") == 4

    _run_threads([lambda n=n: worker(n) for n in range(N_THREADS)])
    with mu:
        rc, out, _, _ = drain_native(
            eng,
            resp(b"GCOUNT", b"GET", b"shared")
            + resp(b"PNCOUNT", b"GET", b"shared")
            + resp(b"TLOG", b"SIZE", b"shared"),
        )
    total = N_THREADS * N_ROUNDS
    assert out == b":%d\r\n:%d\r\n:%d\r\n" % (total, total, total)


def test_memo_install_invalidate_under_mutex(cdll):
    """The UJSON render-memo lifecycle under contention: installer
    threads publish renders (the oracle's job), writer threads bank
    writes that invalidate prefixes, reader threads serve GETs. All
    serialized by the mutex; the memo must end coherent and every
    served render must be one the installers published."""
    eng = ServeEngine(cdll)
    mu = threading.Lock()
    render = b"$7\r\n{\"n\":1}\r\n"

    def installer():
        for _ in range(N_ROUNDS):
            with mu:
                eng.uj_memo_put(b"doc", [], render)
                eng.uj_memo_put(b"doc", [b"n"], b"$1\r\n1\r\n")

    def writer():
        for i in range(N_ROUNDS):
            with mu:
                rc, out, deferred, _ = drain_native(
                    eng, resp(b"UJSON", b"SET", b"doc", b"n", b"%d" % i)
                )
                assert rc == 0 and not deferred
                assert out == b"+OK\r\n"
                assert eng.uj_memo_len(b"doc") == 0  # prefix invalidated

    def reader():
        for _ in range(N_ROUNDS):
            with mu:
                rc, out, deferred, _ = drain_native(
                    eng, resp(b"UJSON", b"GET", b"doc")
                )
                assert rc == 0
                # either a miss (deferred to the oracle) or the
                # installed render, never a torn/stale byte string
                if deferred:
                    assert deferred == [[b"UJSON", b"GET", b"doc"]]
                    assert out == b""
                else:
                    assert out == render

    _run_threads([installer, installer, writer, reader, reader])
    with mu:
        assert eng.uj_memo_len(b"doc") in (0, 2)
        assert eng.uq_count() == 0 or eng.uq_drain() is not None


def test_interner_compaction_under_load(cdll):
    """TLOG value-interner compaction racing (under the mutex) with
    fresh INS traffic on other rows: compaction remaps vids while the
    ingest path interns new values. Every merged entry must still
    resolve to its original bytes afterwards."""
    eng = ServeEngine(cdll)
    mu = threading.Lock()
    with mu:
        row = eng.tlog_upsert(b"hot")
        eng.tlog_ins(row, 1, b"keep-0")
        assert eng.tlog_size(row) == 1  # build the merged-view memo
        for i in range(1, 4000):
            eng.tlog_ins(row, 1 + i, b"garbage-%d" % i)
        eng.tlog_flush_deltas()

    def compactor():
        with mu:
            # drain trims to the top 2 entries -> most vids garbage
            eng.tlog_finish_row(row, 2, 3999)
            eng.tlog_finish_end()
        for _ in range(N_ROUNDS):
            with mu:
                eng.tlog_compact()

    def ingester(n: int):
        for i in range(N_ROUNDS):
            with mu:
                rc, out, deferred, _ = drain_native(
                    eng,
                    resp(b"TLOG", b"INS", b"cold-%d" % n,
                         b"live-%d-%d" % (n, i), b"%d" % (i + 1)),
                )
                assert rc == 0 and not deferred and out == b"+OK\r\n"

    _run_threads([compactor] + [lambda n=n: ingester(n) for n in range(3)])
    with mu:
        size = eng.tlog_size(row)
        assert size == eng.tlog_len_cache(row)
        ents = eng.tlog_merged_entries(row)
        assert ents is not None and len(ents) == size
        for _, val in ents:
            assert val.startswith((b"keep-", b"garbage-"))
        for n in range(3):
            r = eng.tlog_find(b"cold-%d" % n)
            assert eng.tlog_size(r) == N_ROUNDS
