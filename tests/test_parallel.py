"""Sharded merge-path tests on the virtual 8-device CPU mesh.

conftest.py forces 8 virtual CPU devices — the same environment the
driver's dryrun_multichip uses — so these tests validate that the
multi-chip shardings compile and execute without real chips.
"""

import jax
import numpy as np
import pytest

import jylis_tpu  # noqa: F401
from jylis_tpu.ops import planes
from jylis_tpu.parallel import (
    converge_sharded,
    join_replica_axis,
    make_mesh,
    read_all_sharded,
    route_batch,
    shard_plane,
)


def test_mesh_shapes():
    mesh = make_mesh(8)
    assert mesh.devices.shape == (1, 8)
    mesh2 = make_mesh(8, rep=4)
    assert mesh2.devices.shape == (4, 2)
    with pytest.raises(ValueError):
        make_mesh(8, rep=3)
    with pytest.raises(ValueError):
        make_mesh(1000)


def test_route_batch_blocks_pads_and_coalesces():
    rows = np.array([0, 5, 17, 18, 33, 5], np.int32)  # 5 duplicated
    deltas = np.arange(6 * 2, dtype=np.uint64).reshape(6, 2)
    local_rows, d_hi, d_lo = route_batch(rows, deltas, n_shards=4, rows_per_shard=16)
    lr = local_rows.reshape(4, -1)
    assert lr.shape[1] == 2  # padded to the max shard load
    assert list(lr[0]) == [0, 5]
    assert list(lr[1]) == [1, 2]
    assert lr[2][0] == 1
    # pad slots: far out of range AND unique within each shard's slice, so
    # the device-side unique_indices hint stays honest
    assert all(p > 1 << 20 for p in (lr[2][1], lr[3][0], lr[3][1]))
    for shard in lr:
        assert len(set(map(int, shard))) == len(shard)
    # duplicate row 5 max-combined: deltas[1]=[2,3], deltas[5]=[10,11]
    dl = d_lo.reshape(4, 2, 2)
    np.testing.assert_array_equal(dl[0, 1], [10, 11])


def test_sharded_converge_matches_single_chip():
    rng = np.random.default_rng(0)
    K, R, B = 128, 8, 64
    n = 8
    mesh = make_mesh(n)
    reference = np.zeros((K, R), np.uint64)
    hi = shard_plane(mesh, np.zeros((K, R), np.uint32))
    lo = shard_plane(mesh, np.zeros((K, R), np.uint32))
    for _ in range(3):
        rows = rng.integers(0, K, B).astype(np.int32)
        deltas = rng.integers(0, 1 << 48, (B, R)).astype(np.uint64)
        np.maximum.at(reference, rows, deltas)
        lr, dh, dl = route_batch(rows, deltas, n, K // n)
        hi, lo = converge_sharded(mesh, hi, lo, lr, dh, dl)
    got = planes.combine64_np(
        np.asarray(jax.device_get(hi)), np.asarray(jax.device_get(lo))
    )
    np.testing.assert_array_equal(got, reference)
    sums = np.asarray(jax.device_get(read_all_sharded(mesh, hi, lo)))
    np.testing.assert_array_equal(sums, reference.sum(axis=1, dtype=np.uint64))


class _R:
    """Minimal resp sink for driving repos directly."""

    def __init__(self):
        self.vals = []

    def u64(self, v):
        self.vals.append(v)

    def i64(self, v):
        self.vals.append(v)

    def ok(self):
        pass


def test_serving_repos_auto_shard_disjoint_key_blocks():
    """Under the 8-device harness the counter repos serve keys-sharded:
    each device owns a disjoint, contiguous block of key rows covering the
    whole keyspace (VERDICT round-1 item 2)."""
    from jylis_tpu.models.repo_counters import RepoGCOUNT

    repo = RepoGCOUNT(identity=7)
    assert repo._mesh is not None and repo._n_shards == 8
    k = repo._key_cap
    blocks = []
    for shard in repo._state.hi.addressable_shards:
        (rows, cols) = shard.index
        blocks.append((rows.start or 0, rows.stop if rows.stop else k))
        assert cols == slice(None) or (cols.start or 0) == 0  # all replica cols resident
    blocks.sort()
    assert blocks[0][0] == 0 and blocks[-1][1] == k
    for (a0, a1), (b0, b1) in zip(blocks, blocks[1:]):
        assert a1 == b0  # contiguous, non-overlapping
    assert len({b[0] for b in blocks}) == 8


def test_sharded_engine_convergence_two_nodes():
    """Two engine repos (different identities), both in mesh mode, exchange
    flushed deltas and converge to identical values — the reference's
    anti-entropy round (repo_gcount.pony:25-60) on the sharded path."""
    from jylis_tpu.models.repo_counters import RepoGCOUNT, RepoPNCOUNT

    a, b = RepoGCOUNT(identity=1), RepoGCOUNT(identity=2)
    rng = np.random.default_rng(3)
    keys = [b"k%d" % i for i in range(300)]  # > one shard block's worth
    model = {k: 0 for k in keys}
    for repo in (a, b):
        for k in keys:
            amt = int(rng.integers(1, 1000))
            repo.apply(_R(), [b"INC", k, str(amt).encode()])
            model[k] += amt
    for src, dst in ((a, b), (b, a)):
        for key, delta in src.flush_deltas():
            dst.converge(key, delta)
    for repo in (a, b):
        for k in keys:
            r = _R()
            repo.apply(r, [b"GET", k])
            assert r.vals == [model[k]], k

    pa, pb = RepoPNCOUNT(identity=1), RepoPNCOUNT(identity=2)
    pmodel = {k: 0 for k in keys}
    for repo in (pa, pb):
        for k in keys:
            amt = int(rng.integers(1, 1000))
            op = b"INC" if rng.integers(2) else b"DEC"
            repo.apply(_R(), [op, k, str(amt).encode()])
            pmodel[k] += amt if op == b"INC" else -amt
    for src, dst in ((pa, pb), (pb, pa)):
        for key, delta in src.flush_deltas():
            dst.converge(key, delta)
    for repo in (pa, pb):
        for k in keys:
            r = _R()
            repo.apply(r, [b"GET", k])
            assert r.vals == [pmodel[k]], k


def test_sharded_repo_grows_past_initial_capacity():
    """Growth re-places the planes sharded and keeps values intact."""
    from jylis_tpu.models.repo_counters import RepoGCOUNT

    repo = RepoGCOUNT(identity=5, key_cap=16)
    n = 200  # forces several grows past 16
    for i in range(n):
        repo.apply(_R(), [b"INC", b"g%d" % i, b"%d" % (i + 1)])
    # foreign deltas force a real sharded drain
    repo.converge(b"g0", {99: 7})
    repo.drain()
    assert repo._state.hi.shape[0] >= n
    assert len(repo._state.hi.addressable_shards) == 8
    for i in range(n):
        r = _R()
        repo.apply(r, [b"GET", b"g%d" % i])
        assert r.vals == [(i + 1) + (7 if i == 0 else 0)]


def test_sharded_treg_convergence_and_ties():
    """TREG in mesh mode: two repos exchange deltas and agree, including
    a same-timestamp value tie that the host must resolve by string order
    (docs treg.md:56-63) through the routed patch scatter."""
    from jylis_tpu.models.repo_treg import RepoTREG

    class _T:
        def __init__(self):
            self.out = []

        def ok(self):
            pass

        def null(self):
            self.out.append(None)

        def array_start(self, n):
            pass

        def string(self, s):
            self.out.append(s)

        def u64(self, v):
            self.out.append(v)

    a, b = RepoTREG(identity=1), RepoTREG(identity=2)
    assert a._mesh is not None and a._n_shards == 8
    assert len(a._state.vid.addressable_shards) == 8
    rng = np.random.default_rng(5)
    keys = [b"r%d" % i for i in range(200)]
    model: dict[bytes, tuple[int, bytes]] = {}
    for repo in (a, b):
        for k in keys:
            ts = int(rng.integers(1, 1000))
            val = b"v%d" % rng.integers(100)
            repo.apply(_T(), [b"SET", k, val, str(ts).encode()])
            cur = model.get(k)
            if cur is None or (ts, val) > cur:
                model[k] = (ts, val)
    # a tie: same ts, different values -> larger string wins on both nodes
    a.apply(_T(), [b"SET", b"tie", b"apple", b"777"])
    b.apply(_T(), [b"SET", b"tie", b"zebra", b"777"])
    model[b"tie"] = (777, b"zebra")
    for src, dst in ((a, b), (b, a)):
        for key, delta in src.flush_deltas():
            dst.converge(key, delta)
    for repo in (a, b):
        for k in keys + [b"tie"]:
            t = _T()
            repo.apply(t, [b"GET", k])
            want_ts, want_val = model[k]
            assert t.out == [want_val, want_ts], (k, t.out)


def test_sharded_tlog_convergence_trim_and_overflow():
    """TLOG in mesh mode: cross-node log convergence, TRIM through the
    routed trim kernel, and the overflow-retry grow path."""
    from jylis_tpu.models.repo_tlog import RepoTLOG

    class _T:
        def __init__(self):
            self.out = []

        def ok(self):
            pass

        def array_start(self, n):
            self.out.append(("arr", n))

        def string(self, s):
            self.out.append(s)

        def u64(self, v):
            self.out.append(v)

    a, b = RepoTLOG(identity=1, len_cap=4), RepoTLOG(identity=2, len_cap=4)
    assert a._mesh is not None
    assert a._state.wide  # mesh states use the fixed 3-plane layout
    assert len(a._state.ntl.addressable_shards) == 8
    keys = [b"log%d" % i for i in range(40)]
    for repo, base in ((a, 0), (b, 1000)):
        for k in keys:
            for t in range(6):  # 6 entries > len_cap 4: exercises grow
                repo.apply(_T(), [b"INS", k, b"e%d" % (base + t), b"%d" % (base + t + 1)])
    for src, dst in ((a, b), (b, a)):
        for key, delta in src.flush_deltas():
            dst.converge(key, delta)
    for k in keys:
        ra, rb = _T(), _T()
        a.apply(ra, [b"GET", k])
        b.apply(rb, [b"GET", k])
        assert ra.out == rb.out and ra.out[0] == ("arr", 12), k
    # sizes agree cross-node after the sharded drains
    sa, sb = _T(), _T()
    a.apply(sa, [b"SIZE", keys[0]])
    b.apply(sb, [b"SIZE", keys[0]])
    assert sa.out == sb.out == [12]
    # TRIM through the routed kernel: keep 3 newest, cutoff replicates
    a.apply(_T(), [b"TRIM", keys[0], b"3"])
    st = _T()
    a.apply(st, [b"SIZE", keys[0]])
    assert st.out == [3]
    for key, delta in a.flush_deltas():
        b.converge(key, delta)
    sb2 = _T()
    b.apply(sb2, [b"SIZE", keys[0]])
    assert sb2.out == [3]


def test_join_replica_axis_is_lattice_join():
    rng = np.random.default_rng(1)
    S, K = 8, 64  # 2 local rows per rep shard: exercises the local fold
    mesh = make_mesh(8, rep=4)
    states = rng.integers(0, 1 << 62, (S, K)).astype(np.uint64)
    s_hi, s_lo = planes.split64_np(states)
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(mesh, P("rep", "keys"))
    jhi, jlo = join_replica_axis(
        mesh, jax.device_put(s_hi, sh), jax.device_put(s_lo, sh)
    )
    joined = planes.combine64_np(
        np.asarray(jax.device_get(jhi)), np.asarray(jax.device_get(jlo))
    )
    want = np.broadcast_to(states.max(axis=0), (S, K))
    np.testing.assert_array_equal(joined, want)
