"""Sharded merge-path tests on the virtual 8-device CPU mesh.

conftest.py forces 8 virtual CPU devices — the same environment the
driver's dryrun_multichip uses — so these tests validate that the
multi-chip shardings compile and execute without real chips.
"""

import jax
import numpy as np
import pytest

import jylis_tpu  # noqa: F401
from jylis_tpu.parallel import (
    converge_sharded,
    join_replica_axis,
    make_mesh,
    read_all_sharded,
    route_batch,
    shard_counts,
)


def test_mesh_shapes():
    mesh = make_mesh(8)
    assert mesh.devices.shape == (1, 8)
    mesh2 = make_mesh(8, rep=4)
    assert mesh2.devices.shape == (4, 2)
    with pytest.raises(ValueError):
        make_mesh(8, rep=3)
    with pytest.raises(ValueError):
        make_mesh(1000)


def test_route_batch_blocks_and_pads():
    rows = np.array([0, 5, 17, 18, 33], np.int32)
    deltas = np.arange(5 * 2, dtype=np.uint64).reshape(5, 2)
    local_rows, local_deltas = route_batch(rows, deltas, n_shards=4, rows_per_shard=16)
    # shard 0 gets rows 0,5; shard 1 gets 17,18 (local 1,2); shard 2 gets 33
    lr = local_rows.reshape(4, -1)
    assert lr.shape[1] == 2  # padded to the max shard load
    assert list(lr[0]) == [0, 5]
    assert list(lr[1]) == [1, 2]
    assert lr[2][0] == 1 and lr[3][0] == lr[2][1]  # PAD_ROW fills


def test_sharded_converge_matches_single_chip():
    rng = np.random.default_rng(0)
    K, R, B = 128, 8, 64
    n = 8
    mesh = make_mesh(n)
    counts = np.zeros((K, R), np.uint64)
    sharded = shard_counts(mesh, counts)
    reference = counts.copy()
    for _ in range(3):
        rows = rng.integers(0, K, B).astype(np.int32)
        deltas = rng.integers(0, 1 << 32, (B, R)).astype(np.uint64)
        np.maximum.at(reference, rows, deltas)
        lr, ld = route_batch(rows, deltas, n, K // n)
        sharded = converge_sharded(mesh, sharded, lr, ld)
    got = np.asarray(jax.device_get(sharded))
    np.testing.assert_array_equal(got, reference)
    sums = np.asarray(jax.device_get(read_all_sharded(mesh, sharded)))
    np.testing.assert_array_equal(sums, reference.sum(axis=1, dtype=np.uint64))


def test_join_replica_axis_is_lattice_join():
    rng = np.random.default_rng(1)
    S, K = 4, 64
    mesh = make_mesh(8, rep=4)
    states = rng.integers(0, 1 << 40, (S, K)).astype(np.uint64)
    from jax.sharding import NamedSharding, PartitionSpec as P

    placed = jax.device_put(states, NamedSharding(mesh, P("rep", "keys")))
    joined = np.asarray(jax.device_get(join_replica_axis(mesh, placed)))
    want = np.broadcast_to(states.max(axis=0), (S, K))
    np.testing.assert_array_equal(joined, want)
