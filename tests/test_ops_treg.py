"""Property + differential tests for the TREG device kernel.

Semantics oracle: docs/_docs/types/treg.md:56-63 via hostref.TReg. Exercises
the rank-prefix tie-break and the host tie-resolution contract (prefix
collisions surface as a tie mask, never as a wrong silent winner). The
kernel stores ts/rank as hi/lo u32 planes (ops/planes.py), so tests split
u64 inputs the same way the repo layer does.
"""

import numpy as np
import pytest

import jylis_tpu  # noqa: F401
from jylis_tpu.ops import hostref, planes, treg
from jylis_tpu.ops.interner import Interner, prefix_rank

K = 32


def get_ts(state, k) -> int:
    return int(
        planes.combine64_np(
            np.asarray(state.ts_hi[k]), np.asarray(state.ts_lo[k])
        )
    )


def test_prefix_rank_order_preserving():
    vals = [b"", b"a", b"ab", b"abc", b"b", b"zzzzzzzzz", b"\xff" * 4]
    for x in vals:
        for y in vals:
            rx, ry = prefix_rank(x), prefix_rank(y)
            if rx < ry:
                assert x < y
            elif rx > ry:
                assert x > y


def split_batch(d_ts, d_rank):
    th, tl = planes.split64_np(d_ts)
    rh, rl = planes.split64_np(d_rank)
    return th, tl, rh, rl


def apply_ops(state, interner, ops):
    """ops: list of (key, value, ts). Applies one batch per op (unique-key
    contract trivially satisfied); resolves tie rows on host like the repo
    layer does."""
    for key, value, ts in ops:
        vid = interner.intern(value)
        ki = np.array([key], dtype=np.int32)
        d_ts = np.array([ts], dtype=np.uint64)
        d_rank = np.array([prefix_rank(value)], dtype=np.uint64)
        d_vid = np.array([vid], dtype=np.int32)
        prev_vid = int(np.asarray(state.vid[ki])[0])
        state, tie = treg.set_batch(
            state, ki, *split_batch(d_ts, d_rank), d_vid
        )
        if bool(np.asarray(tie)[0]):
            # host resolves: full string comparison decides the winner
            cur = interner.lookup(prev_vid)
            winner = d_vid if value > cur else np.array([prev_vid], np.int32)
            state = state._replace(vid=state.vid.at[ki].set(winner))
    return state


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_treg_matches_hostref(seed):
    rng = np.random.default_rng(seed)
    interner = Interner()
    state = treg.init(K)
    refs = [hostref.TReg() for _ in range(K)]

    ops = []
    for _ in range(200):
        key = int(rng.integers(0, K))
        # small value/ts spaces to force ties and prefix collisions
        value = bytes(rng.integers(97, 99, size=int(rng.integers(0, 12))))
        ts = int(rng.integers(0, 4))
        ops.append((key, value, ts))
        refs[key].write(value, ts)

    state = apply_ops(state, interner, ops)

    for k in range(K):
        got_vid = int(np.asarray(state.vid[k]))
        want = refs[k].read()
        if want is None:
            assert got_vid == -1
        else:
            assert got_vid >= 0
            assert (interner.lookup(got_vid), get_ts(state, k)) == want


def test_treg_ts_across_u32_boundary():
    """Timestamps straddling 2^32 must compare by the full 64-bit value."""
    interner = Interner()
    state = treg.init(2)
    big, small = (1 << 32) + 7, (1 << 32) - 1
    state = apply_ops(state, interner, [(0, b"old", big), (0, b"new", small)])
    assert interner.lookup(int(np.asarray(state.vid[0]))) == b"old"
    assert get_ts(state, 0) == big


def test_treg_unset_loses_to_zero_ts_write():
    interner = Interner()
    state = treg.init(2)
    state = apply_ops(state, interner, [(0, b"", 0)])
    assert int(np.asarray(state.vid[0])) == interner.intern(b"")  # set
    assert int(np.asarray(state.vid[1])) == -1  # still unset


@pytest.mark.parametrize("seed", [0, 1])
def test_treg_converge_dense_matches_sparse(seed):
    """The dense full-keyspace join must agree with the scatter composite;
    identity rows (0, 0, 0, 0, -1) must never win or tie."""
    rng = np.random.default_rng(seed)
    present = rng.random(K) < 0.7
    rows = np.nonzero(present)[0].astype(np.int32)
    d_ts = np.where(present, rng.integers(0, 4, K), 0).astype(np.uint64)
    d_rank = np.where(present, rng.integers(0, 3, K), 0).astype(np.uint64)
    d_vid = np.where(present, rng.integers(0, 50, K), -1).astype(np.int32)

    # pre-populate both states identically
    pre_ts = rng.integers(0, 4, K).astype(np.uint64)
    pre_rank = rng.integers(0, 3, K).astype(np.uint64)
    pre_vid = rng.integers(0, 50, K).astype(np.int32)
    th, tl, rh, rl = split_batch(pre_ts, pre_rank)
    base, _ = treg.converge_dense(treg.init(K), th, tl, rh, rl, pre_vid)

    th, tl, rh, rl = split_batch(d_ts, d_rank)
    dense, tie_d = treg.converge_dense(base, th, tl, rh, rl, d_vid)
    sparse, tie_s = treg.converge_batch(
        base, rows, th[rows], tl[rows], rh[rows], rl[rows], d_vid[rows]
    )
    for plane in ("ts_hi", "ts_lo", "rank_hi", "rank_lo", "vid"):
        np.testing.assert_array_equal(
            np.asarray(getattr(dense, plane)), np.asarray(getattr(sparse, plane))
        )
    np.testing.assert_array_equal(np.asarray(tie_d)[rows], np.asarray(tie_s))
    assert not np.asarray(tie_d)[~present].any()  # identity never ties


def test_treg_converge_many_scan():
    """Replica batches folded in one compiled scan must equal sequential."""
    rng = np.random.default_rng(9)
    interner = Interner()
    n_batches, B = 8, 16
    vals = [bytes([97 + i]) for i in range(26)]
    vids = np.array([interner.intern(v) for v in vals], dtype=np.int32)
    ranks = np.array([prefix_rank(v) for v in vals], dtype=np.uint64)

    ki = rng.integers(0, K, size=(n_batches, B)).astype(np.int32)
    # unique keys within each batch (contract)
    for i in range(n_batches):
        ki[i] = rng.permutation(K)[:B]
    pick = rng.integers(0, len(vals), size=(n_batches, B))
    d_ts = rng.integers(0, 1000, size=(n_batches, B)).astype(np.uint64)
    d_vid = vids[pick]
    d_rank = ranks[pick]
    th, tl, rh, rl = split_batch(d_ts, d_rank)

    seq = treg.init(K)
    for i in range(n_batches):
        seq, _ = treg.converge_batch(
            seq, ki[i], th[i], tl[i], rh[i], rl[i], d_vid[i]
        )

    scanned, _ = treg.converge_many(treg.init(K), ki, th, tl, rh, rl, d_vid)
    for plane in ("ts_hi", "ts_lo", "vid"):
        np.testing.assert_array_equal(
            np.asarray(getattr(seq, plane)), np.asarray(getattr(scanned, plane))
        )
