"""Multi-lane serving: keyspace slicing, the lane bus/bridge, per-lane
journal segments with merge replay, SO_REUSEPORT sharing, SYSTEM
DIGEST, and the supervisor's metrics aggregation.

The bridge topology is exercised IN-PROCESS (the bus is literally the
existing Cluster engine on loopback, so two Databases + three Cluster
instances in one loop model lane 0 + lane 1 + an external peer
exactly); the spawned end-to-end path (supervisor, SO_REUSEPORT
sharding, lanes.json, cross-process convergence) lives in the chaos
lane-crash cell in test_drill_matrix.py.
"""

import asyncio
import json
import os

import pytest

import jylis_tpu  # noqa: F401
from test_cluster import TICK, Node, converge_wait, grab_ports, resp_call
from jylis_tpu import lanes as lanes_mod
from jylis_tpu import journal as journal_mod
from jylis_tpu.cluster import Cluster
from jylis_tpu.models.database import Database
from jylis_tpu.server.server import Server
from jylis_tpu.system import System
from jylis_tpu.utils.address import Address
from jylis_tpu.utils.config import Config, resolve_auto_lanes
from jylis_tpu.utils.log import Log
from jylis_tpu.utils.metrics import metric_lines


# ---- slicing / config ------------------------------------------------------


def test_lane_of_stable_and_in_range():
    keys = [b"k%d" % i for i in range(500)]
    for n in (1, 2, 4, 7):
        owners = [lanes_mod.lane_of(k, n) for k in keys]
        assert all(0 <= o < n for o in owners)
        assert owners == [lanes_mod.lane_of(k, n) for k in keys]
    # a non-degenerate spread: every lane owns something at 500 keys
    assert len(set(lanes_mod.lane_of(k, 4) for k in keys)) == 4


def test_auto_lanes_resolution():
    assert resolve_auto_lanes(1) == 1
    assert resolve_auto_lanes(2) == 1  # a lane split would just contend
    assert resolve_auto_lanes(4) == 4
    assert resolve_auto_lanes(64) == 8  # capped


def test_lane_identities_distinct_and_restart_stable():
    cfg = Config()
    cfg.addr = Address("10.0.0.1", "9999", "prod-node")
    cfg.lanes = 4
    cfg.lane_bus = [7001, 7002, 7003, 7004]
    ids = {lanes_mod.lane_identity(cfg, k) for k in range(4)}
    assert len(ids) == 4  # distinct CRDT replica identities per lane
    assert cfg.addr.hash64() not in ids
    # restart-stable: a reboot picks fresh ephemeral bus ports, and the
    # identity must NOT change with them (a port-derived identity would
    # mint N new replica ids per restart, growing counter columns
    # forever)
    cfg2 = Config()
    cfg2.addr = cfg.addr
    cfg2.lanes = 4
    cfg2.lane_bus = [8101, 8102, 8103, 8104]
    assert ids == {lanes_mod.lane_identity(cfg2, k) for k in range(4)}


def test_bus_config_seeds_exclude_self():
    cfg = Config()
    cfg.addr = Address("10.0.0.1", "9999", "n")
    cfg.lanes = 3
    cfg.lane_bus = [7001, 7002, 7003]
    bc = lanes_mod.bus_config(cfg, 1)
    assert bc.addr == lanes_mod.bus_address(cfg, 1)
    assert bc.addr not in bc.seed_addrs
    assert len(bc.seed_addrs) == 2
    assert bc.heartbeat_time == cfg.lane_bus_heartbeat


# ---- per-lane journal segments ---------------------------------------------


def test_segment_names():
    assert journal_mod.segment_name(None) == "journal.jylis"
    assert journal_mod.segment_name(2) == "journal.lane2.jylis"
    assert lanes_mod.snapshot_name(None) == "snapshot.jylis"
    assert lanes_mod.snapshot_name(3) == "snapshot.lane3.jylis"


def _journal_write(path: str, name: str, batch) -> None:
    j = journal_mod.Journal(path, fsync="off")
    j.open()
    j.append(name, batch)
    j.flush()
    j.close()


def test_recover_all_merges_every_lane_segment(tmp_path):
    d = str(tmp_path)
    _journal_write(
        os.path.join(d, "journal.lane0.jylis"), "GCOUNT", [(b"a", {1: 5})]
    )
    _journal_write(
        os.path.join(d, "journal.lane1.jylis"), "GCOUNT", [(b"b", {2: 7})]
    )
    # the classic single-lane segment merges too (a node that moved
    # from --lanes 1 to --lanes N keeps its history)
    _journal_write(
        os.path.join(d, "journal.jylis"), "GCOUNT", [(b"c", {3: 9})]
    )
    db = Database(identity=42)
    n = journal_mod.recover_all(
        db, d, os.path.join(d, "journal.lane0.jylis")
    )
    assert n == 3
    resp = _Collect()
    for key, want in ((b"a", b":5"), (b"b", b":7"), (b"c", b":9")):
        resp.vals.clear()
        db.apply(resp, [b"GCOUNT", b"GET", key])
        assert resp.vals == ["u64", int(want[1:])], (key, resp.vals)


def test_recover_all_never_mutates_foreign_torn_tail(tmp_path):
    d = str(tmp_path)
    own = os.path.join(d, "journal.lane0.jylis")
    foreign = os.path.join(d, "journal.lane1.jylis")
    _journal_write(own, "GCOUNT", [(b"a", {1: 5})])
    _journal_write(foreign, "GCOUNT", [(b"b", {2: 7})])
    # a live sibling mid-append: torn trailing bytes on the FOREIGN file
    with open(foreign, "ab") as f:
        f.write(b"\x00\x01\x02")
    size_before = os.path.getsize(foreign)
    db = Database(identity=42)
    n = journal_mod.recover_all(db, d, own)
    assert n == 2  # both complete batches converged
    # the foreign file was not truncated and not moved aside
    assert os.path.getsize(foreign) == size_before
    assert not os.path.exists(foreign + ".unreadable")


def test_recover_all_skips_corrupt_foreign_segment(tmp_path):
    d = str(tmp_path)
    own = os.path.join(d, "journal.lane0.jylis")
    foreign = os.path.join(d, "journal.lane1.jylis")
    _journal_write(own, "GCOUNT", [(b"a", {1: 5})])
    with open(foreign, "wb") as f:
        f.write(b"not a journal at all")
    db = Database(identity=42)
    n = journal_mod.recover_all(db, d, own)
    assert n == 1
    # never mutate another lane's file, even an unreadable one
    assert os.path.exists(foreign)
    assert not os.path.exists(foreign + ".unreadable")


# ---- SO_REUSEPORT ----------------------------------------------------------


def test_reuseport_two_servers_share_one_port():
    async def main():
        (port,) = grab_ports(1)
        cfgs, servers = [], []
        for _ in range(2):
            cfg = Config()
            cfg.port = str(port)
            cfg.lanes = 2  # arms the SO_REUSEPORT listener path
            cfg.log = Log.create_none()
            cfgs.append(cfg)
            servers.append(Server(cfg, Database(identity=1)))
        for s in servers:
            await s.start()  # the second bind would raise without SO_REUSEPORT
        try:
            for _ in range(8):
                out = await resp_call(
                    port, b"*4\r\n$6\r\nGCOUNT\r\n$3\r\nINC\r\n$1\r\nk\r\n$1\r\n1\r\n"
                )
                assert out == b"+OK\r\n", out
        finally:
            for s in servers:
                await s.dispose()

    asyncio.run(main())


# ---- the lane bus + lane-0 bridge, in-process ------------------------------


class LaneStack:
    """One in-process lane: Database + bus Cluster (+ external Cluster
    and bridge on lane 0), the exact wiring main.py does for a worker."""

    def __init__(self, config, lane_id: int, ext_seeds=()):
        self.config = config
        self.lane_id = lane_id
        bus_cfg = lanes_mod.bus_config(config, lane_id)
        self.system = System(bus_cfg)
        self.database = Database(
            identity=lanes_mod.lane_identity(config, lane_id),
            system_repo=self.system.repo,
        )
        self.system.repo.lane_fn = lambda: {
            "id": lane_id, "count": config.lanes
        }
        self.bus = Cluster(
            bus_cfg, self.database, register_system=(lane_id != 0)
        )
        self.external = None
        if lane_id == 0:
            ext_cfg = Config()
            ext_cfg.port = "0"
            ext_cfg.addr = config.addr
            ext_cfg.seed_addrs = list(ext_seeds)
            ext_cfg.heartbeat_time = TICK
            ext_cfg.log = config.log
            self.external = Cluster(ext_cfg, self.database, drive_flush=False)
            lanes_mod.wire_bridge(self.bus, self.external)
        srv_cfg = Config()
        srv_cfg.port = "0"
        srv_cfg.log = config.log
        self.server = Server(srv_cfg, self.database)

    async def start(self):
        await self.server.start()
        await self.bus.start()
        if self.external is not None:
            await self.external.start()

    async def stop(self):
        self.bus.dispose()
        if self.external is not None:
            self.external.dispose()
        await self.server.dispose()


async def _make_lane_pair(ext_seeds=()):
    b0, b1, ext_port = grab_ports(3)
    cfg = Config()
    cfg.addr = Address("127.0.0.1", str(ext_port), "lanenode")
    cfg.lanes = 2
    cfg.lane_bus = [b0, b1]
    cfg.lane_bus_heartbeat = TICK
    cfg.log = Log.create_none()
    lane0 = LaneStack(cfg, 0, ext_seeds=ext_seeds)
    lane1 = LaneStack(cfg, 1)
    await lane0.start()
    await lane1.start()
    return cfg, lane0, lane1


async def _gcount(port: int, key: bytes):
    out = await resp_call(
        port, b"*3\r\n$6\r\nGCOUNT\r\n$3\r\nGET\r\n$%d\r\n%s\r\n" % (len(key), key)
    )
    return out


def test_lanes_converge_over_bus():
    """A write accepted by one lane becomes readable on the other —
    serve-after-converge across the loopback bus."""

    async def main():
        cfg, lane0, lane1 = await _make_lane_pair()
        try:
            out = await resp_call(
                lane1.server.port,
                b"*4\r\n$6\r\nGCOUNT\r\n$3\r\nINC\r\n$1\r\nk\r\n$1\r\n7\r\n",
            )
            assert out == b"+OK\r\n", out

            async def converged():
                return await _gcount(lane0.server.port, b"k") == b":7\r\n"

            deadline = asyncio.get_event_loop().time() + 200 * TICK
            while asyncio.get_event_loop().time() < deadline:
                if await converged():
                    break
                await asyncio.sleep(TICK)
            assert await converged()
        finally:
            await lane0.stop()
            await lane1.stop()

    asyncio.run(main())


def test_bridge_relays_between_lanes_and_external_peer():
    """Lane 1's writes reach an external peer through lane 0's bridge,
    and the peer's writes reach lane 1 — one cluster identity outside,
    full fan-in inside."""

    async def main():
        (peer_port,) = grab_ports(1)
        peer = Node("peer", peer_port)
        await peer.start()
        try:
            cfg, lane0, lane1 = await _make_lane_pair(
                ext_seeds=[peer.config.addr]
            )
            try:
                assert await converge_wait(
                    lambda: any(
                        c.established
                        for c in lane0.external._actives.values()
                    ),
                    ticks=200,
                )
                # lane 1 -> bus -> lane 0 bridge -> external peer
                out = await resp_call(
                    lane1.server.port,
                    b"*4\r\n$6\r\nGCOUNT\r\n$3\r\nINC\r\n$1\r\nx\r\n$1\r\n5\r\n",
                )
                assert out == b"+OK\r\n", out
                # peer -> lane 0 external -> bridge -> bus -> lane 1
                peer.database.apply(_Collect(), [b"GCOUNT", b"INC", b"y", b"3"])

                async def both():
                    a = await _gcount(peer.server.port, b"x")
                    b = await _gcount(lane1.server.port, b"y")
                    return a == b":5\r\n" and b == b":3\r\n"

                deadline = asyncio.get_event_loop().time() + 400 * TICK
                while asyncio.get_event_loop().time() < deadline:
                    if await both():
                        break
                    await asyncio.sleep(TICK)
                assert await both()
            finally:
                await lane0.stop()
                await lane1.stop()
        finally:
            await peer.stop()

    asyncio.run(main())


# ---- SYSTEM DIGEST / LANE metrics ------------------------------------------


class _Collect:
    def __init__(self):
        self.vals = []

    def __getattr__(self, name):
        return lambda *a: self.vals.extend((name, *a))


def test_system_digest_async_path_and_convergence():
    """SYSTEM DIGEST over a real RESP connection: equal on converged
    replicas, different when they diverge."""

    async def main():
        p_a, p_b = grab_ports(2)
        a = Node("aye", p_a)
        b = Node("bee", p_b, seeds=[a.config.addr])
        await a.start()
        await b.start()
        try:
            digest_cmd = b"*2\r\n$6\r\nSYSTEM\r\n$6\r\nDIGEST\r\n"
            empty_a = await resp_call(a.server.port, digest_cmd)
            empty_b = await resp_call(b.server.port, digest_cmd)
            assert empty_a.startswith(b"$64\r\n"), empty_a
            assert empty_a == empty_b  # both empty: equal digests
            out = await resp_call(
                a.server.port,
                b"*4\r\n$6\r\nGCOUNT\r\n$3\r\nINC\r\n$1\r\nk\r\n$1\r\n2\r\n",
            )
            assert out == b"+OK\r\n"

            async def matched():
                da = await resp_call(a.server.port, digest_cmd)
                db = await resp_call(b.server.port, digest_cmd)
                return da == db and da != empty_a

            deadline = asyncio.get_event_loop().time() + 300 * TICK
            while asyncio.get_event_loop().time() < deadline:
                if await matched():
                    break
                await asyncio.sleep(TICK)
            assert await matched()
        finally:
            await b.stop()
            await a.stop()

    asyncio.run(main())


def test_system_digest_sync_path_matches_async():
    db = Database(identity=9)
    resp = _Collect()
    db.apply(resp, [b"GCOUNT", b"INC", b"k", b"4"])
    resp.vals.clear()
    db.apply(resp, [b"SYSTEM", b"DIGEST"])
    assert resp.vals[0] == "string"
    sync_hex = resp.vals[1]

    async def async_digest():
        return (await db.sync_digest_async()).hex().encode()

    assert asyncio.run(async_digest()) == sync_hex


def test_metric_lines_lane_section():
    lines = metric_lines(lane={"id": 2, "count": 4})
    assert lines[0] == "LANE id 2"
    assert lines[1] == "LANE count 4"
    # single-lane nodes: no section at all (byte-stable legacy surface)
    assert not any(
        line.startswith("LANE") for line in metric_lines()
    )


# ---- metrics aggregation ---------------------------------------------------


def test_aggregate_expositions_relabels_and_sums():
    body0 = (
        "# HELP jylis_cmds_total Commands served per data type.\n"
        "# TYPE jylis_cmds_total counter\n"
        'jylis_cmds_total{type="GCOUNT"} 10\n'
        'jylis_gauge{name="cluster.backlog_ms"} 1.5\n'
        'jylis_seam_latency_seconds_count{seam="server.py_dispatch"} 4\n'
        "jylis_trace_events 2\n"
    )
    body1 = (
        "# HELP jylis_cmds_total Commands served per data type.\n"
        "# TYPE jylis_cmds_total counter\n"
        'jylis_cmds_total{type="GCOUNT"} 32\n'
        'jylis_gauge{name="cluster.backlog_ms"} 0.5\n'
        'jylis_seam_latency_seconds_count{seam="server.py_dispatch"} 6\n'
        "jylis_trace_events 1\n"
    )
    out = lanes_mod.aggregate_expositions({0: body0, 1: body1, 2: None})
    # per-lane relabeled samples
    assert 'jylis_cmds_total{lane="0",type="GCOUNT"} 10' in out
    assert 'jylis_cmds_total{lane="1",type="GCOUNT"} 32' in out
    # counters sum into the aggregate (lane-less) series
    assert 'jylis_cmds_total{type="GCOUNT"} 42' in out
    assert (
        'jylis_seam_latency_seconds_count{seam="server.py_dispatch"} 10'
        in out
    )
    assert "jylis_trace_events 3" in out
    # gauges stay per-lane only (summing a backlog is meaningless)
    assert 'jylis_gauge{name="cluster.backlog_ms"} 2' not in out
    assert 'jylis_gauge{lane="0",name="cluster.backlog_ms"} 1.5' in out
    # a dead lane is visible, not an error
    assert 'jylis_lane_up{lane="2"} 0' in out
    assert 'jylis_lane_up{lane="0"} 1' in out
    # HELP/TYPE emitted once
    assert out.count("# TYPE jylis_cmds_total counter") == 1


def test_aggregate_output_is_valid_exposition():
    import re

    sample_re = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
        r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\""
        r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\")*\})?"
        r" -?[0-9.eE+-]+( [0-9]+)?$"
    )
    out = lanes_mod.aggregate_expositions(
        {0: 'jylis_cmds_total{type="GCOUNT"} 10\njylis_trace_events 2\n'}
    )
    for line in out.splitlines():
        if line and not line.startswith("#"):
            assert sample_re.match(line), line


# ---- supervisor plumbing (no processes) ------------------------------------


def test_parse_lane_failpoints():
    got = lanes_mod._parse_lane_failpoints("1:lane.tick=crash:1;0:x=error")
    assert got == {1: "lane.tick=crash:1", 0: "x=error"}
    assert lanes_mod._parse_lane_failpoints("") == {}
    assert lanes_mod._parse_lane_failpoints("junk") == {}


def test_supervisor_child_argv_overrides(tmp_path):
    async def main():
        cfg = Config()
        cfg.port = "0"
        cfg.addr = Address("127.0.0.1", "9999", "supnode")
        cfg.lanes = 2
        cfg.data_dir = str(tmp_path)
        cfg.log = Log.create_none()
        sup = lanes_mod.Supervisor(
            cfg, ["--port", "0", "--lanes", "2", "--addr", "127.0.0.1:9999:"]
        )
        argv = sup._child_argv(1)
        assert argv[:3] == [__import__("sys").executable, "-m", "jylis_tpu"]
        # the appended overrides win under argparse (last occurrence)
        assert argv[argv.index("--lane-id") + 1] == "1"
        assert str(sup.resp_port) == argv[len(argv) - argv[::-1].index("--port")]
        assert argv[-2] == "--metrics-port"
        # lanes.json round-trips through write_manifest
        sup.write_manifest()
        manifest = json.load(open(os.path.join(str(tmp_path), "lanes.json")))
        assert manifest["port"] == sup.resp_port
        assert [lane["id"] for lane in manifest["lanes"]] == [0, 1]

    asyncio.run(main())


def test_supervisor_manifest_write_runs_off_the_event_loop(tmp_path, monkeypatch):
    """Regression (jlint v2 interprocedural JL101): `run()` and
    `_lane_died()` called `write_manifest` — open/json.dump/os.replace —
    directly on the supervisor event loop, which also carries every
    lane's death-watcher, signal handling, and the aggregated metrics
    endpoint. A contended disk during a crash-respawn storm stalled all
    three. The write now dispatches through write_manifest_async: a
    slow manifest write must not freeze the loop."""

    async def main():
        import threading
        import time as _time

        cfg = Config()
        cfg.port = "0"
        cfg.addr = Address("127.0.0.1", "9999", "supnode")
        cfg.lanes = 2
        cfg.data_dir = str(tmp_path)
        cfg.log = Log.create_none()
        sup = lanes_mod.Supervisor(cfg, ["--port", "0", "--lanes", "2"])

        wrote_on: list = []
        real = lanes_mod.Supervisor.write_manifest

        def slow_write(self):
            wrote_on.append(threading.current_thread())
            _time.sleep(0.3)  # the contended-disk shape
            real(self)

        monkeypatch.setattr(lanes_mod.Supervisor, "write_manifest", slow_write)

        # a loop heartbeat: the largest gap between ticks is the stall
        gaps: list[float] = []

        async def ticker():
            last = asyncio.get_running_loop().time()
            while True:
                await asyncio.sleep(0.01)
                now = asyncio.get_running_loop().time()
                gaps.append(now - last)
                last = now

        t = asyncio.ensure_future(ticker())
        try:
            await sup.write_manifest_async()
        finally:
            t.cancel()
        assert wrote_on and wrote_on[0] is not threading.main_thread()
        # the loop kept ticking THROUGH the 0.3 s write (pre-fix the
        # direct call would produce one >=0.3 s gap)
        assert gaps and max(gaps) < 0.15, max(gaps)
        # and the manifest really landed
        manifest = json.load(open(os.path.join(str(tmp_path), "lanes.json")))
        assert [lane["id"] for lane in manifest["lanes"]] == [0, 1]

    asyncio.run(main())


def test_lane_died_writes_manifest_off_loop(tmp_path, monkeypatch):
    """The crash-respawn path itself (`_lane_died`) must use the
    threaded manifest write — pinned by driving it with a stubbed spawn
    and asserting the write thread."""

    async def main():
        import threading

        cfg = Config()
        cfg.port = "0"
        cfg.addr = Address("127.0.0.1", "9999", "supnode")
        cfg.lanes = 2
        cfg.data_dir = str(tmp_path)
        cfg.log = Log.create_none()
        sup = lanes_mod.Supervisor(cfg, ["--port", "0", "--lanes", "2"])
        monkeypatch.setattr(lanes_mod, "RESTART_BACKOFF_S", 0.0)
        monkeypatch.setattr(
            lanes_mod.Supervisor, "_spawn", lambda self, k: None
        )
        wrote_on: list = []
        real = lanes_mod.Supervisor.write_manifest

        def recording_write(self):
            wrote_on.append(threading.current_thread())
            real(self)

        monkeypatch.setattr(
            lanes_mod.Supervisor, "write_manifest", recording_write
        )
        await sup._lane_died(1)
        assert wrote_on and wrote_on[0] is not threading.main_thread()

    asyncio.run(main())


def test_concurrent_manifest_writes_serialise(tmp_path, monkeypatch):
    """Two lanes dying near-simultaneously drive write_manifest_async
    concurrently; the writes share ONE fixed lanes.json.tmp path, so
    they must serialise (the on-loop call was implicitly serial; the
    off-loop fix carries an explicit lock) — interleaved writers would
    publish corrupt JSON."""

    async def main():
        import time as _time

        cfg = Config()
        cfg.port = "0"
        cfg.addr = Address("127.0.0.1", "9999", "supnode")
        cfg.lanes = 2
        cfg.data_dir = str(tmp_path)
        cfg.log = Log.create_none()
        sup = lanes_mod.Supervisor(cfg, ["--port", "0", "--lanes", "2"])
        spans: list = []
        real = lanes_mod.Supervisor.write_manifest

        def slow_write(self):
            t0 = _time.monotonic()
            _time.sleep(0.15)
            real(self)
            spans.append((t0, _time.monotonic()))

        monkeypatch.setattr(lanes_mod.Supervisor, "write_manifest", slow_write)
        await asyncio.gather(
            sup.write_manifest_async(), sup.write_manifest_async()
        )
        assert len(spans) == 2
        (a0, a1), (b0, b1) = sorted(spans)
        assert b0 >= a1, "concurrent manifest writes overlapped"
        # and the published file is valid JSON
        manifest = json.load(open(os.path.join(str(tmp_path), "lanes.json")))
        assert [lane["id"] for lane in manifest["lanes"]] == [0, 1]

    asyncio.run(main())
