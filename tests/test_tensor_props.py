"""TENSOR property tests beyond the generated law harness: the RESP
surface (SET/GET/MRG, shape/mode rejection at the boundary), NaN/±inf
coordinate semantics, LWW tiebreak determinism across replica ids
(same digest on every replica for every delivery order), and the
journal/snapshot/flush round-trips of the new delta payload."""

import math
import itertools
import struct

import pytest

from jylis_tpu.cluster import codec
from jylis_tpu.models.database import Database
from jylis_tpu.models.repo_tensor import PENDING_DRAIN_THRESHOLD
from jylis_tpu.ops.tensor_host import (
    CANON_NAN_BITS,
    MODE_LWW,
    Tensor,
    canon_f32,
    pack_f32,
)


class Cap:
    """Records resp-protocol calls for assertion."""

    def __init__(self):
        self.calls = []

    def __getattr__(self, name):
        def rec(*a):
            self.calls.append((name, a))

        return rec

    def last_err(self):
        return next(a[0] for n, a in reversed(self.calls) if n == "err")

    def last_vec(self):
        """The payload bulk of the most recent GET reply array
        (string calls per GET are [mode, vector])."""
        strings = [a[0] for n, a in self.calls if n == "string"]
        return strings[-1] if len(strings) >= 2 else None


def _set(db, resp, key, mode, ts, payload):
    db.apply(resp, [b"TENSOR", b"SET", key, mode, str(ts).encode(), payload])


def _get_vec(db, key):
    r = Cap()
    db.apply(r, [b"TENSOR", b"GET", key])
    return r.last_vec()


# ---- RESP boundary rejection ------------------------------------------------


def test_payload_dtype_rejected_at_resp_boundary():
    db = Database(identity=1, engine="python")
    for bad in (b"", b"abc", b"12345"):
        r = Cap()
        _set(db, r, b"k", b"MAX", 0, bad)
        assert "BADSHAPE" in r.last_err(), bad
    # the key was never created by a rejected write
    r = Cap()
    db.apply(r, [b"TENSOR", b"GET", b"k"])
    assert r.calls == [("null", ())]


def test_mode_and_dim_mismatch_rejected():
    db = Database(identity=1, engine="python")
    ok = Cap()
    _set(db, ok, b"k", b"MAX", 0, pack_f32([1.0, 2.0]))
    assert ok.calls[-1][0] == "ok"
    r = Cap()
    _set(db, r, b"k", b"LWW", 3, pack_f32([1.0, 2.0]))
    assert "BADSHAPE (key holds MAX/2, write is LWW/2)" in r.last_err()
    r = Cap()
    _set(db, r, b"k", b"MAX", 0, pack_f32([1.0, 2.0, 3.0]))
    assert "write is MAX/3" in r.last_err()
    # MRG passes the same gate
    r = Cap()
    blob = codec.encode_delta("TENSOR", Tensor.lww(pack_f32([9.0, 9.0]), 1, 1))
    db.apply(r, [b"TENSOR", b"MRG", b"k", blob])
    assert "BADSHAPE" in r.last_err()
    r = Cap()
    db.apply(r, [b"TENSOR", b"MRG", b"k", b"\x99garbage"])
    assert "BADPAYLOAD" in r.last_err()


def test_unknown_mode_renders_help():
    db = Database(identity=1, engine="python")
    r = Cap()
    _set(db, r, b"k", b"SUM", 0, pack_f32([1.0]))
    assert "BADCOMMAND" in r.last_err()


# ---- NaN / ±inf coordinates -------------------------------------------------


def test_nan_canonicalises_and_is_max_top():
    db = Database(identity=1, engine="python")
    r = Cap()
    # a NON-canonical NaN payload (sign bit + junk mantissa)
    weird_nan = struct.pack("<I", 0xFFC00001)
    _set(db, r, b"k", b"MAX", 0, weird_nan + struct.pack("<f", 1.0))
    got = _get_vec(db, b"k")
    assert struct.unpack("<I", got[:4])[0] == CANON_NAN_BITS
    # NaN is the per-coordinate top: +inf does not displace it, and the
    # bytes stay canonical (digest-stable on every replica)
    _set(db, r, b"k", b"MAX", 0, pack_f32([math.inf, math.inf]))
    got = _get_vec(db, b"k")
    assert struct.unpack("<I", got[:4])[0] == CANON_NAN_BITS
    assert struct.unpack("<f", got[4:])[0] == math.inf


def test_inf_ordering_and_negzero_total_order():
    assert canon_f32(pack_f32([-math.inf])) == pack_f32([-math.inf])
    a = Tensor.max_value(pack_f32([-math.inf, -0.0]))
    b = Tensor.max_value(pack_f32([-1e30, 0.0]))
    a.converge(b)
    got = struct.unpack("<2f", a.val)
    assert got[0] == pytest.approx(-1e30) and math.copysign(1, got[1]) == 1.0


def test_avg_zero_weight_fallback_is_a_clean_unweighted_mean():
    """All-zero-ts AVG keys render the UNWEIGHTED mean; the weighted
    pass's 0*inf = NaN contamination must not leak into the fallback."""
    t = Tensor.avg(1, 0, pack_f32([math.inf, 2.0]))
    t.converge(Tensor.avg(2, 0, pack_f32([4.0, 6.0])))
    vec, ts = t.read()
    assert ts == 0
    got = struct.unpack("<2f", vec)
    assert got[0] == math.inf and got[1] == pytest.approx(4.0), got


def test_avg_with_nan_inf_is_replica_deterministic():
    contribs = [
        Tensor.avg(1, 2, pack_f32([math.nan, 1.0])),
        Tensor.avg(2, 3, pack_f32([math.inf, 2.0])),
        Tensor.avg(3, 1, pack_f32([-math.inf, 4.0])),
    ]
    reads = set()
    for perm in itertools.permutations(contribs):
        t = Tensor()
        for c in perm:
            t.converge(c)
        reads.add(t.read())
    assert len(reads) == 1


# ---- LWW tiebreak determinism across replicas ------------------------------


def test_lww_equal_ts_tiebreak_same_digest_on_all_replicas():
    """Three replicas write the same key at the SAME timestamp; every
    delivery order on every replica must settle on identical canonical
    state (the rid tiebreak) — the digest-match acceptance in miniature."""
    writes = {
        rid: Tensor.lww(pack_f32([float(rid), -float(rid)]), 7, rid)
        for rid in (1, 2, 3)
    }
    canons = set()
    for perm in itertools.permutations(writes.values()):
        t = Tensor()
        for w in perm:
            t.converge(w)
        canons.add(t.canon())
    assert len(canons) == 1
    settled = next(iter(canons))
    # the rid-3 write wins every coordinate
    assert settled[2] == pack_f32([3.0, -3.0])


def test_lww_tiebreak_through_full_database_digests():
    dbs = {rid: Database(identity=rid, engine="python") for rid in (1, 2, 3)}
    flushed = {}
    for rid, db in dbs.items():
        r = Cap()
        _set(db, r, b"emb", b"LWW", 7, pack_f32([float(rid), 1.0]))
        out = []
        db.flush_deltas(lambda batch: out.append(batch))
        flushed[rid] = [b for b in out if b[0] == "TENSOR"]
    for rid, db in dbs.items():
        for other, batches in sorted(flushed.items()):
            if other == rid:
                continue
            for name, batch in batches:
                db.converge_deltas((name, batch))
    digests = {db._sync_digest_blocking() for db in dbs.values()}
    assert len(digests) == 1
    for db in dbs.values():
        assert _get_vec(db, b"emb") == pack_f32([3.0, 1.0])


# ---- wire/journal/snapshot round-trips -------------------------------------


def test_wire_rejects_malformed_planes():
    t = Tensor.lww(pack_f32([1.0, 2.0]), 5, 9)
    blob = bytearray(codec.encode_delta("TENSOR", t))
    # truncating the val plane must fail decode, not mis-shape state
    with pytest.raises(codec.CodecError):
        codec.decode_delta("TENSOR", bytes(blob[:-1]))
    # a structurally valid but shape-inconsistent delta is refused
    bad = Tensor()
    bad.mode, bad.dim, bad.val = MODE_LWW, 2, pack_f32([1.0, 2.0])
    bad.ts, bad.rid = b"", b""
    with pytest.raises(codec.CodecError):
        codec.decode_delta("TENSOR", codec.encode_delta("TENSOR", bad))


def test_threshold_drain_keeps_get_exact():
    db = Database(identity=1, engine="python")
    repo = db.manager("TENSOR").repo
    r = Cap()
    for i in range(PENDING_DRAIN_THRESHOLD + 5):
        _set(db, r, b"k%d" % i, b"MAX", 0, pack_f32([float(i)]))
    assert repo._tbl.pend_count() < PENDING_DRAIN_THRESHOLD
    assert _get_vec(db, b"k0") == pack_f32([0.0])
    assert _get_vec(db, b"k%d" % PENDING_DRAIN_THRESHOLD) == pack_f32(
        [float(PENDING_DRAIN_THRESHOLD)]
    )


def test_mrg_rejects_over_u64_contribution_ts():
    """A wire varint admits ~2^77; a contribution ts past u64 must be
    refused at decode, not accepted into the lattice where the next
    drain's u64 planes would raise (and boot replay would crash-loop
    on the journaled delta)."""
    t = Tensor.avg(1, 3, pack_f32([1.0]))
    t.contribs[1] = (1 << 64, t.contribs[1][1])
    blob = codec.encode_delta("TENSOR", t)
    with pytest.raises(codec.CodecError):
        codec.decode_delta("TENSOR", blob)
    db = Database(identity=1, engine="python")
    r = Cap()
    db.apply(r, [b"TENSOR", b"MRG", b"k", blob])
    assert "BADPAYLOAD" in r.last_err()
    # and the repo still drains cleanly afterwards
    db.manager("TENSOR").repo.drain()


def test_avg_device_mirror_tracks_host_winner():
    """Equal-(rid, ts) AVG contributions with different vectors join
    WHOLE-vector on the host (lexicographic (ts, okey-tuple)); the
    device mirror must land exactly the host winner, never a
    per-coordinate mix of both vectors — and a stale remote delta must
    not regress the mirror below the host truth."""
    import numpy as np

    db = Database(identity=1, engine="python")
    repo = db.manager("TENSOR").repo
    r = Cap()
    for vec in ([1.0, 9.0], [2.0, 3.0]):
        blob = codec.encode_delta("TENSOR", Tensor.avg(7, 5, pack_f32(vec)))
        db.apply(r, [b"TENSOR", b"MRG", b"k", blob])
        repo.drain()
    # host whole-vector winner at the (rid=7, ts=5) tie
    w = repo._tbl.winner(repo._tbl.find(b"k"))
    assert w.contribs[7] == (5, pack_f32([2.0, 3.0]))
    dev = repo._dev_rows[repo._tbl.find(b"k")][7]
    got = np.asarray(repo._state.val[dev, :2]).tobytes()
    assert got == pack_f32([2.0, 3.0]), got
    # a STALE contribution (older ts) buffers and drains without
    # regressing either the host winner or the mirror
    blob = codec.encode_delta("TENSOR", Tensor.avg(7, 4, pack_f32([8.0, 8.0])))
    db.apply(r, [b"TENSOR", b"MRG", b"k", blob])
    repo.drain()
    w = repo._tbl.winner(repo._tbl.find(b"k"))
    assert w.contribs[7] == (5, pack_f32([2.0, 3.0]))
    got = np.asarray(repo._state.val[dev, :2]).tobytes()
    assert got == pack_f32([2.0, 3.0]), got


def test_dominance_flip_retires_device_rows():
    """Replication can upgrade a key's (mode, dim) rank wholesale (two
    nodes racing first-writes of a fresh key); the device mirror must
    follow — the old rank's planes would otherwise pin coordinates the
    monotone select can never regress (e.g. okey(1.0) < okey(5.0))."""
    import numpy as np

    db = Database(identity=1, engine="python")
    repo = db.manager("TENSOR").repo
    r = Cap()
    _set(db, r, b"k", b"MAX", 0, pack_f32([5.0]))
    repo.drain()
    row = repo._tbl.find(b"k")
    old_dev = repo._dev_rows[row][-1]
    # a replicated MAX/dim-2 write dominates the MAX/dim-1 state
    repo.converge(b"k", Tensor.max_value(pack_f32([1.0, 1.0])))
    repo.drain()
    w = repo._tbl.winner(row)
    assert (w.mode, w.dim) == (1, 2) and w.val == pack_f32([1.0, 1.0])
    new_dev = repo._dev_rows[row][-1]
    assert new_dev != old_dev
    got = np.asarray(repo._state.val[new_dev, :2]).tobytes()
    assert got == pack_f32([1.0, 1.0]), got
    # an AVG flip likewise re-homes (and re-mirrors every contribution)
    repo.converge(b"k", Tensor.avg(9, 3, pack_f32([4.0, 4.0, 4.0])))
    repo.drain()
    dev = repo._dev_rows[row][9]
    assert -1 not in repo._dev_rows[row]
    got = np.asarray(repo._state.val[dev, :3]).tobytes()
    assert got == pack_f32([4.0, 4.0, 4.0]), got


def test_snapshot_and_journal_round_trip(tmp_path):
    from jylis_tpu import persist
    from jylis_tpu.journal import journal as journal_mod

    db = Database(identity=1, engine="python")
    r = Cap()
    _set(db, r, b"m", b"MAX", 0, pack_f32([5.0, -1.0]))
    _set(db, r, b"l", b"LWW", 9, pack_f32([2.0]))
    db.apply(r, [
        b"TENSOR", b"MRG", b"a",
        codec.encode_delta("TENSOR", Tensor.avg(4, 6, pack_f32([8.0]))),
    ])
    jpath = str(tmp_path / "journal.jylis")
    j = journal_mod.Journal(jpath, fsync="always")
    j.open()
    db.set_journal(j)
    db.flush_deltas(lambda batch: None)
    j.flush()
    j.close()
    want = db._sync_digest_blocking()

    spath = str(tmp_path / "snap.jylis")
    persist.save_snapshot(db, spath)
    db2 = Database(identity=2, engine="python")
    persist.load_snapshot(db2, spath)
    assert db2._sync_digest_blocking() == want

    db3 = Database(identity=3, engine="python")
    assert journal_mod.replay_journal(db3, jpath) > 0
    assert db3._sync_digest_blocking() == want
