"""Mesh-mode cluster churn soak (round-5 verdict item 4, nightly
`make soak`): REAL node processes on the 8-virtual-device mesh — every
keyspace born keys-sharded across the mesh, exactly how a pod-slice
node serves — driven through write / SIGKILL-mid-traffic / rejoin /
digest-sync / converge over a sharded keyspace.

This differs from test_soak_scale.py (plain single-device CPU nodes,
keyspace size as the stressor) in what it stresses: here every drain is
a sharded device program (parallel/sharded.py), so the churn exercises
recovery + anti-entropy + journal replay THROUGH the mesh path — the
combination the driver's dryrun compiles but nothing previously ran
end-to-end under crash churn."""

from __future__ import annotations

import os
import random
import signal
import socket
import subprocess
import sys
import time

import pytest

from procutil import REPO, connect_client, free_port

from jylis_tpu.client import Client

# spawn on the virtual 8-device mesh (the smoke3 boot line): repos come
# up keys-sharded instead of single-device
SPAWN_MESH = (
    "from jylis_tpu.utils.vcpu import force_virtual_cpu; "
    "force_virtual_cpu(8); "
    "import sys; from jylis_tpu.main import main; main(sys.argv[1:])"
)

# sized for the virtual mesh: every drain is a sharded 8-device XLA
# program, ~100x a single-device dict drain on this CPU harness — the
# soak exercises the mesh-path recovery machinery, not keyspace scale
# (test_soak_scale.py owns that, on single-device nodes)
N_G, N_PN, N_T, N_L, N_U = 600, 300, 300, 150, 150
CHUNK = 1_000


def spawn_mesh_node(port, cport, name, *extra) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-c", SPAWN_MESH, "--port", str(port), "--addr",
         f"127.0.0.1:{cport}:{name}", "--log-level", "warn", *extra],
        cwd=REPO,
    )


def stop_node(proc, grace: float = 120.0) -> None:
    proc.terminate()
    try:
        proc.wait(timeout=grace)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=10)


def _pipeline(port: int, cmds: list[bytes]) -> None:
    s = socket.create_connection(("127.0.0.1", port), timeout=300)
    try:
        for i in range(0, len(cmds), CHUNK):
            chunk = cmds[i : i + CHUNK]
            s.sendall(b"\r\n".join(chunk) + b"\r\n")
            got, buf = 0, b""
            while got < len(chunk):
                data = s.recv(1 << 20)
                if not data:
                    raise ConnectionError("node closed during load")
                buf += data
                got = buf.count(b"\r\n")
            bad = [l for l in buf.split(b"\r\n") if l.startswith(b"-")]
            assert not bad, bad[:3]
    finally:
        s.close()


def _read(port: int, *args):
    with Client("127.0.0.1", port, timeout=60) as c:
        return c.execute_command(*args)


def _until(fn, what: str, deadline_s: float = 900.0):
    deadline = time.time() + deadline_s
    last = None
    while time.time() < deadline:
        try:
            if fn():
                return
        except (OSError, RuntimeError, AssertionError) as e:
            # node still syncing/restarting/recompiling — retried; any
            # other exception is a bug in the soak itself and raises
            last = e
        time.sleep(0.5)
    raise AssertionError(f"timed out waiting for {what} (last error: {last})")


@pytest.mark.soak
@pytest.mark.slow  # nightly (`make soak`), not per-commit
def test_mesh_cluster_churn_write_kill_rejoin_converge(tmp_path):
    rng = random.Random(11)
    ports = [free_port() for _ in range(3)]
    cports = [free_port() for _ in range(3)]
    names = ["mesh-a", "mesh-b", "mesh-c"]
    datas = [str(tmp_path / f"data{i}") for i in range(3)]
    seed_addr = f"127.0.0.1:{cports[0]}:{names[0]}"

    def boot(i):
        extra = ["--data-dir", datas[i], "--snapshot-interval", "2",
                 "--heartbeat-time", "0.2"]
        if i > 0:
            extra += ["--seed-addrs", seed_addr]
        else:
            # the seed logs sync responses at info level: the digest-
            # match rejoin below is asserted through SYSTEM GETLOG
            extra += ["--log-level", "info"]
        return spawn_mesh_node(ports[i], cports[i], names[i], *extra)

    procs = [boot(i) for i in range(3)]
    try:
        for p, pr in zip(ports, procs):
            connect_client(p, proc=pr).close()

        # ---- sharded keyspace, writes spread over all three nodes ---------
        load: list[list[bytes]] = [[], [], []]
        for i in range(N_G):
            load[i % 3].append(b"GCOUNT INC mg%05d %d" % (i, i % 89 + 1))
        for i in range(N_PN):
            load[i % 3].append(b"PNCOUNT INC mp%05d %d" % (i, i % 31 + 3))
            load[(i + 1) % 3].append(b"PNCOUNT DEC mp%05d 2" % i)
        for i in range(N_T):
            load[i % 3].append(b"TREG SET mt%05d v%d %d" % (i, i, i + 1))
        for i in range(N_L):
            load[i % 3].append(b"TLOG INS ml%04d e%d %d" % (i, i, i + 1))
        for i in range(N_U):
            load[i % 3].append(b"UJSON INS mu%04d tags %d" % (i, i))
        for n, cmds in enumerate(load):
            _pipeline(ports[n], cmds)

        samples = [rng.randrange(N_G) for _ in range(30)]

        def converged(port):
            for i in samples:
                if _read(port, "GCOUNT", "GET", "mg%05d" % i) != i % 89 + 1:
                    return False
            for i in (0, N_PN - 1):
                if _read(port, "PNCOUNT", "GET", "mp%05d" % i) != i % 31 + 1:
                    return False
            if _read(port, "TREG", "GET", "mt00007") != [b"v7", 8]:
                return False
            if _read(port, "TLOG", "SIZE", "ml0003") != 1:
                return False
            return _read(port, "UJSON", "GET", "mu0009", "tags") == b"9"

        for p in ports:
            _until(lambda p=p: converged(p),
                   f"initial sharded-keyspace convergence on :{p}")

        # ---- SIGKILL node C MID-TRAFFIC, keep writing, rejoin -------------
        extra_cmds = [b"GCOUNT INC missed%04d 7" % i for i in range(1_000)]
        half = len(extra_cmds) // 2
        _pipeline(ports[0], extra_cmds[:half])
        procs[2].send_signal(signal.SIGKILL)  # mid-traffic: no snapshot cut
        procs[2].wait(timeout=30)
        _pipeline(ports[0], extra_cmds[half:])

        procs[2] = boot(2)
        connect_client(ports[2], proc=procs[2]).close()

        def c_rejoined():
            for i in (0, half, len(extra_cmds) - 1):
                if _read(ports[2], "GCOUNT", "GET", "missed%04d" % i) != 7:
                    return False
            return converged(ports[2])

        _until(c_rejoined, "killed node re-syncs the sharded keyspace")
        # (no journal-metrics assertion here: with --snapshot-interval 2
        # the 2s compaction cadence legitimately leaves an empty active
        # segment at SIGKILL time — journal replay under crash churn is
        # pinned by test_journal.py and test_soak.py on the single-device
        # path, which shares all the journal code)

        # ---- quiesce, kill/rejoin again: digest-gated catch-up ------------
        time.sleep(2.0)  # let delta traffic quiesce so digests settle
        procs[2].send_signal(signal.SIGKILL)
        procs[2].wait(timeout=30)
        procs[2] = boot(2)
        connect_client(ports[2], proc=procs[2]).close()

        def rejoin_digest_matched():
            if not converged(ports[2]):
                return False
            log_lines = _read(ports[0], "SYSTEM", "GETLOG")
            flat = b"\n".join(
                e[0] if isinstance(e, list) else e for e in log_lines
            )
            return b"digest match" in flat

        _until(rejoin_digest_matched, "in-sync mesh rejoin digest-matches")

        # ---- final cross-node agreement on fresh post-churn writes --------
        assert _read(ports[2], "TREG", "SET", "final", "done", 99) == b"OK"
        for p in ports:
            _until(
                lambda p=p: _read(p, "TREG", "GET", "final") == [b"done", 99],
                f"post-churn TREG convergence on :{p}", 120,
            )
    finally:
        for pr in procs:
            stop_node(pr)
