"""Native RESP scanner: build check + differential tests vs the Python
parser (the semantic oracle), plus the jlint pass-11 semantic-parity
pins (full-Server byte differentials over the grammar edge cases the
symbolic extraction verified).

The native library is built lazily by jylis_tpu.native.lib() with g++ (in
this environment the toolchain is baked in); if a build is genuinely
impossible the suite must still reveal that, so the build test is a hard
assertion, not a skip.
"""

import os
import sys

import numpy as np
import pytest

from jylis_tpu.native import lib
from jylis_tpu.native.resp import NativeRespParser, make_parser
from jylis_tpu.server.resp import RespError, RespParser

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def test_native_lib_builds_and_loads():
    assert lib() is not None


def make_native() -> NativeRespParser:
    cdll = lib()
    assert cdll is not None
    return NativeRespParser(cdll)


def drain(parser, data: bytes):
    parser.append(data)
    return list(parser)


CASES = [
    b"*4\r\n$6\r\nGCOUNT\r\n$3\r\nINC\r\n$3\r\nfoo\r\n$1\r\n9\r\n",
    b"*1\r\n$0\r\n\r\n",
    b"*0\r\n",
    b"TREG SET k hello 5\r\n",
    b"  spaced   out\tcmd  \r\n",
    b"\r\n*2\r\n$1\r\na\r\n$1\r\nb\r\n",  # blank inline line, then array
    b"PING\r\nPING\r\n*1\r\n$4\r\nPING\r\n",  # pipelined mix
]


@pytest.mark.parametrize("data", CASES)
def test_matches_python_parser(data):
    want = drain(RespParser(), data)
    got = drain(make_native(), data)
    assert got == want


@pytest.mark.parametrize("data", CASES)
def test_matches_python_parser_byte_at_a_time(data):
    py, nat = RespParser(), make_native()
    want, got = [], []
    for i in range(len(data)):
        py.append(data[i : i + 1])
        nat.append(data[i : i + 1])
        want.extend(py)
        got.extend(nat)
    assert got == want


ERROR_CASES = [
    b"*2\r\n$abc\r\n",
    b"*x\r\n",
    b"*+2\r\n$1\r\na\r\n$1\r\nb\r\n",  # strict: no leading +
    b"*1\r\n:5\r\n",  # not a bulk string
    b"*1\r\n$3\r\nabcX\r\n",  # bad terminator
    b"*-1\r\n",  # negative array
    b"*1\r\n$-1\r\n",  # negative bulk
]


@pytest.mark.parametrize("data", ERROR_CASES)
def test_protocol_errors_agree(data):
    with pytest.raises(RespError):
        drain(RespParser(), data)
    with pytest.raises(RespError):
        drain(make_native(), data)


def test_truncated_input_agrees_with_oracle():
    for data in CASES:
        py, nat = RespParser(), make_native()
        assert drain(nat, data[:-1]) == drain(py, data[:-1])


def test_arg_array_growth():
    from jylis_tpu.native.resp import _INITIAL_ARGS

    n = _INITIAL_ARGS * 2  # forces the rc == -2 grow-and-rescan branch
    parts = b"".join(b"$1\r\nx\r\n" for _ in range(n))
    got = drain(make_native(), b"*%d\r\n" % n + parts)
    assert got == [[b"x"] * n]


def test_fuzz_differential():
    rng = np.random.default_rng(0)
    tokens = [
        b"*", b"$", b"\r\n", b"1", b"3", b"9", b"a", b"GCOUNT", b" ",
        b"INC", b"\r", b"\n", b"-", b"x" * 17,
    ]
    for _ in range(300):
        blob = b"".join(
            tokens[i] for i in rng.integers(0, len(tokens), rng.integers(1, 12))
        )
        py, nat = RespParser(), make_native()
        try:
            want = drain(py, blob)
            perr = None
        except RespError:
            want, perr = None, True
        try:
            got = drain(nat, blob)
            nerr = None
        except RespError:
            got, nerr = None, True
        assert (perr, want) == (nerr, got), blob


def test_make_parser_returns_native_here():
    assert isinstance(make_parser(), NativeRespParser)


@pytest.mark.parametrize("data", ERROR_CASES)
def test_protocol_error_messages_match_oracle(data):
    """Both serving paths must reply identical error BYTES on malformed
    input, not merely both error (client-visible parity)."""
    with pytest.raises(RespError) as want:
        drain(RespParser(), data)
    with pytest.raises(RespError) as got:
        drain(make_native(), data)
    assert str(got.value) == str(want.value)


# ---- jlint pass 11 semantic-parity pins ------------------------------
#
# Pass 11 (scripts/jlint/pass_semantics.py) symbolically extracts every
# natively-served command's grammar from the C++ and diffs it against
# the Python oracle's; the sweep found ZERO divergences, and each pin
# below freezes one equivalence the extraction leans on hardest — the
# edge of a numeric bound, an optionality rule, a validator gate. Each
# runs one stream through the REAL Server twice (native vs forced-
# Python) and byte-compares the replies, so a regression on either side
# of the seam fails with the exact diverging bytes.

# these boot the full Server/Database (jax-importing); the ASAN/TSAN
# runs of this module cover the scanner only
_sanitize = pytest.mark.skipif(
    os.environ.get("JYLIS_SANITIZE") == "1",
    reason="server drive imports jax; sanitize runs are jax-free",
)


def _pin(stream):
    from scripts.gen_semfuzz import run_stream_differential

    run_stream_differential(stream)


@_sanitize
def test_pin_u64_bounds_and_leading_zeros():
    """parse_u64 edge parity: leading zeros are decimal (007 == 7),
    U64_MAX is accepted, one past it (and any sign/junk) rejects —
    native strict_u64 and the oracle's parse_u64 must agree on every
    boundary, byte for byte."""
    _pin([
        [b"GCOUNT", b"INC", b"k", b"007"],
        [b"GCOUNT", b"GET", b"k"],
        [b"GCOUNT", b"INC", b"max", b"18446744073709551615"],
        [b"GCOUNT", b"GET", b"max"],
        [b"GCOUNT", b"INC", b"over", b"18446744073709551616"],
        [b"GCOUNT", b"INC", b"neg", b"-1"],
        [b"GCOUNT", b"INC", b"plus", b"+2"],
        [b"GCOUNT", b"INC", b"sp", b" 1"],
        [b"GCOUNT", b"GET", b"over"],
    ])


@_sanitize
def test_pin_empty_key_and_binary_key():
    """Keys are raw bytes on both sides: empty and CR/NUL-bearing keys
    round-trip identically through counters and TREG."""
    _pin([
        [b"GCOUNT", b"INC", b"", b"1"],
        [b"GCOUNT", b"GET", b""],
        [b"TREG", b"SET", b"\x00\xff", b"v", b"3"],
        [b"TREG", b"GET", b"\x00\xff"],
        [b"TREG", b"GET", b""],
    ])


@_sanitize
def test_pin_arity_and_unknown_subcommand_defer():
    """Wrong arity and unknown subcommands are NOT native errors — the
    native front-end defers them and the oracle renders the help text,
    so both server paths emit identical bytes (the manifest's
    error_mode: defer contract)."""
    _pin([
        [b"GCOUNT", b"GET", b"k", b"extra"],
        [b"GCOUNT", b"INC", b"k"],
        [b"GCOUNT", b"DEC", b"k", b"1"],  # polarity: DEC is PNCOUNT-only
        [b"PNCOUNT", b"NOPE", b"k"],
        [b"TREG", b"SET", b"k", b"v"],  # missing ts
        [b"UJSON"],
    ])


@_sanitize
def test_pin_tlog_optional_count():
    """TLOG GET's arg 3 is parse_opt_count on both sides: absent OR
    unparseable means 'all entries', a parseable value truncates — the
    native optional-u64 extraction pins exactly this."""
    _pin([
        [b"TLOG", b"INS", b"l", b"e1", b"10"],
        [b"TLOG", b"INS", b"l", b"e2", b"20"],
        [b"TLOG", b"GET", b"l"],
        [b"TLOG", b"GET", b"l", b"1"],
        [b"TLOG", b"GET", b"l", b"zz"],  # unparseable -> all
        [b"TLOG", b"GET", b"l", b"0"],
        [b"TLOG", b"GET", b"l", b"18446744073709551615"],
    ])


@_sanitize
def test_pin_ujson_validator_gates():
    """The UJSON native validators (prim/doc JSON shape, UTF-8 paths)
    must split accept/defer exactly where the oracle splits ok/error:
    valid writes bank natively, invalid ones defer and the oracle's
    error bytes come back identical on both paths."""
    _pin([
        [b"UJSON", b"SET", b"d", b"n", b"1"],
        [b"UJSON", b"GET", b"d"],
        [b"UJSON", b"INS", b"d", b"bad", b"{not json}"],
        [b"UJSON", b"SET", b"d", b"\xff\xfe", b"1"],  # invalid-UTF-8 path
        [b"UJSON", b"SET", b"d", "café".encode(), b"2"],
        [b"UJSON", b"GET", b"d", b"n"],
        [b"UJSON", b"CLR", b"d"],
        [b"UJSON", b"GET", b"d"],
    ])
