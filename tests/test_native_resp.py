"""Native RESP scanner: build check + differential tests vs the Python
parser (the semantic oracle).

The native library is built lazily by jylis_tpu.native.lib() with g++ (in
this environment the toolchain is baked in); if a build is genuinely
impossible the suite must still reveal that, so the build test is a hard
assertion, not a skip.
"""

import numpy as np
import pytest

from jylis_tpu.native import lib
from jylis_tpu.native.resp import NativeRespParser, make_parser
from jylis_tpu.server.resp import RespError, RespParser


def test_native_lib_builds_and_loads():
    assert lib() is not None


def make_native() -> NativeRespParser:
    cdll = lib()
    assert cdll is not None
    return NativeRespParser(cdll)


def drain(parser, data: bytes):
    parser.append(data)
    return list(parser)


CASES = [
    b"*4\r\n$6\r\nGCOUNT\r\n$3\r\nINC\r\n$3\r\nfoo\r\n$1\r\n9\r\n",
    b"*1\r\n$0\r\n\r\n",
    b"*0\r\n",
    b"TREG SET k hello 5\r\n",
    b"  spaced   out\tcmd  \r\n",
    b"\r\n*2\r\n$1\r\na\r\n$1\r\nb\r\n",  # blank inline line, then array
    b"PING\r\nPING\r\n*1\r\n$4\r\nPING\r\n",  # pipelined mix
]


@pytest.mark.parametrize("data", CASES)
def test_matches_python_parser(data):
    want = drain(RespParser(), data)
    got = drain(make_native(), data)
    assert got == want


@pytest.mark.parametrize("data", CASES)
def test_matches_python_parser_byte_at_a_time(data):
    py, nat = RespParser(), make_native()
    want, got = [], []
    for i in range(len(data)):
        py.append(data[i : i + 1])
        nat.append(data[i : i + 1])
        want.extend(py)
        got.extend(nat)
    assert got == want


ERROR_CASES = [
    b"*2\r\n$abc\r\n",
    b"*x\r\n",
    b"*+2\r\n$1\r\na\r\n$1\r\nb\r\n",  # strict: no leading +
    b"*1\r\n:5\r\n",  # not a bulk string
    b"*1\r\n$3\r\nabcX\r\n",  # bad terminator
    b"*-1\r\n",  # negative array
    b"*1\r\n$-1\r\n",  # negative bulk
]


@pytest.mark.parametrize("data", ERROR_CASES)
def test_protocol_errors_agree(data):
    with pytest.raises(RespError):
        drain(RespParser(), data)
    with pytest.raises(RespError):
        drain(make_native(), data)


def test_truncated_input_agrees_with_oracle():
    for data in CASES:
        py, nat = RespParser(), make_native()
        assert drain(nat, data[:-1]) == drain(py, data[:-1])


def test_arg_array_growth():
    from jylis_tpu.native.resp import _INITIAL_ARGS

    n = _INITIAL_ARGS * 2  # forces the rc == -2 grow-and-rescan branch
    parts = b"".join(b"$1\r\nx\r\n" for _ in range(n))
    got = drain(make_native(), b"*%d\r\n" % n + parts)
    assert got == [[b"x"] * n]


def test_fuzz_differential():
    rng = np.random.default_rng(0)
    tokens = [
        b"*", b"$", b"\r\n", b"1", b"3", b"9", b"a", b"GCOUNT", b" ",
        b"INC", b"\r", b"\n", b"-", b"x" * 17,
    ]
    for _ in range(300):
        blob = b"".join(
            tokens[i] for i in rng.integers(0, len(tokens), rng.integers(1, 12))
        )
        py, nat = RespParser(), make_native()
        try:
            want = drain(py, blob)
            perr = None
        except RespError:
            want, perr = None, True
        try:
            got = drain(nat, blob)
            nerr = None
        except RespError:
            got, nerr = None, True
        assert (perr, want) == (nerr, got), blob


def test_make_parser_returns_native_here():
    assert isinstance(make_parser(), NativeRespParser)


@pytest.mark.parametrize("data", ERROR_CASES)
def test_protocol_error_messages_match_oracle(data):
    """Both serving paths must reply identical error BYTES on malformed
    input, not merely both error (client-visible parity)."""
    with pytest.raises(RespError) as want:
        drain(RespParser(), data)
    with pytest.raises(RespError) as got:
        drain(make_native(), data)
    assert str(got.value) == str(want.value)
