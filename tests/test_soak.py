"""Bounded soak: a REAL node under sustained mixed churn.

Excluded from the per-commit suite (`-m soak` runs it; CI's nightly job
does). One spawned server takes ~30 seconds of continuous writes across
all five types — TREG overwrite churn (interner epoch compactions),
TLOG inserts with periodic TRIMs, counter increments, UJSON edits,
online snapshots every second — with spot reads checked against host
models throughout, and the process RSS must plateau: the memory at the
end may not grow more than 50% over the reading taken after the first
third (the interner-leak class of bug shows up here as monotonic
growth).
"""

from __future__ import annotations

import os
import time

import pytest

from procutil import free_port, connect_client, spawn_node, stop_node

from jylis_tpu.client import Client

SOAK_SECONDS = 30


def _rss_kb(pid: int) -> int:
    with open(f"/proc/{pid}/statm") as f:
        pages = int(f.read().split()[1])
    return pages * (os.sysconf("SC_PAGE_SIZE") // 1024)


@pytest.mark.soak
@pytest.mark.slow  # nightly (`make soak`), not per-commit
def test_thirty_second_mixed_churn_soak(tmp_path):
    port, cport = free_port(), free_port()
    data = str(tmp_path / "data")
    proc = spawn_node(
        port, cport, "soaknode",
        "--data-dir", data, "--snapshot-interval", "1",
    )
    try:
        c = connect_client(port, proc=proc)

        gcount = 0
        pn = 0
        treg: dict[int, tuple[bytes, int]] = {}
        tlog_n = 0
        rss_at_third = None
        start = time.time()
        i = 0
        while time.time() - start < SOAK_SECONDS:
            i += 1
            k = i % 500
            # TREG overwrite churn: every round replaces values, so the
            # interner must compact or RSS grows forever
            val = b"v%d-%d" % (k, i)
            assert c.execute_command("TREG", "SET", "r%d" % k, val, i) == b"OK"
            treg[k] = (val, i)
            assert c.execute_command("GCOUNT", "INC", "g", 1) == b"OK"
            gcount += 1
            assert c.execute_command("PNCOUNT", "DEC" if i % 3 else "INC", "p", 2) == b"OK"
            pn += 2 if i % 3 == 0 else -2
            assert c.execute_command("TLOG", "INS", "l", b"e%d" % i, i) == b"OK"
            tlog_n += 1
            if i % 400 == 0:
                assert c.execute_command("TLOG", "TRIM", "l", 50) == b"OK"
                tlog_n = 50
            if i % 7 == 0:
                assert c.execute_command(
                    "UJSON", "SET", "d", "f%d" % (i % 16), "%d" % i
                ) == b"OK"
            if i % 250 == 0:
                # spot reads against the host models
                assert c.execute_command("GCOUNT", "GET", "g") == gcount
                assert c.execute_command("PNCOUNT", "GET", "p") == pn
                want_val, want_ts = treg[k]
                assert c.execute_command("TREG", "GET", "r%d" % k) == [want_val, want_ts]
                size = c.execute_command("TLOG", "SIZE", "l")
                assert size == tlog_n, (size, tlog_n)
            if rss_at_third is None and time.time() - start > SOAK_SECONDS / 3:
                rss_at_third = _rss_kb(proc.pid)
        assert rss_at_third is not None, "soak too short to sample RSS"
        rss_end = _rss_kb(proc.pid)
        assert rss_end < rss_at_third * 1.5, (
            f"RSS grew {rss_at_third}kB -> {rss_end}kB during steady churn"
        )
        # final coherence + a live metrics read
        assert c.execute_command("GCOUNT", "GET", "g") == gcount
        metrics = c.execute_command("SYSTEM", "METRICS")
        assert any(line.startswith(b"TREG drains") for line in metrics)
    finally:
        stop_node(proc)


@pytest.mark.soak
@pytest.mark.slow  # two full node boots: nightly (`make soak`), not per-commit
def test_sigkill_mid_traffic_journal_recovery(tmp_path):
    """The journal's acceptance bar: a solo node (NO peers, online
    snapshots OFF — recovery has only the journal to work with) takes
    sustained mixed writes, is SIGKILLed mid-traffic, and the restart
    must recover EVERY delta batch that was flushed before the kill.
    Verified by digest equality: a pre-kill dump of a quiesced write set
    (flushed + fsynced, confirmed via the JOURNAL metrics) must read
    back identically after restart."""
    import signal

    data = str(tmp_path / "data")
    port, cport = free_port(), free_port()
    extra = (
        "--data-dir", data, "--heartbeat-time", "0.2",
        "--journal-fsync", "interval", "--journal-fsync-interval", "0.05",
    )
    proc = spawn_node(port, cport, "jsoak", *extra)
    killed_mid_write = False
    try:
        c = connect_client(port, proc=proc)
        # phase A: the tracked write set, across all five types
        gcount = 0
        for i in range(300):
            k = i % 40
            assert c.execute_command("TREG", "SET", "r%d" % k, b"a%d" % i, i + 1) == b"OK"
            assert c.execute_command("GCOUNT", "INC", "g", 3) == b"OK"
            gcount += 3
            assert c.execute_command("PNCOUNT", "DEC", "p", 1) == b"OK"
            assert c.execute_command("TLOG", "INS", "l%d" % k, b"e%d" % i, i + 1) == b"OK"
            if i % 5 == 0:
                assert c.execute_command(
                    "UJSON", "SET", "d", "f%d" % k, "%d" % i
                ) == b"OK"
        # quiesce: wait until the heartbeat flush + fsync interval have
        # certainly covered phase A, confirmed by the journal metrics
        deadline = time.time() + 60
        appends = 0
        while time.time() < deadline:
            metrics = c.execute_command("SYSTEM", "METRICS")
            by = dict(
                line.rsplit(b" ", 1)
                for line in metrics
                if line.startswith(b"JOURNAL")
            )
            appends = int(by.get(b"JOURNAL appends", b"0"))
            if appends >= 5 and int(by.get(b"JOURNAL fsyncs", b"0")) >= 1:
                break
            time.sleep(0.2)
        assert appends >= 5, "phase-A deltas never reached the journal"
        time.sleep(1.0)  # > heartbeat + proactive + fsync intervals
        # the pre-kill dump: phase A's exact expected reads
        pre = {}
        for k in range(40):
            pre[("TREG", "r%d" % k)] = c.execute_command("TREG", "GET", "r%d" % k)
            pre[("TLOG", "l%d" % k)] = c.execute_command("TLOG", "GET", "l%d" % k)
            pre[("UJSON", "f%d" % k)] = c.execute_command("UJSON", "GET", "d", "f%d" % k)
        pre[("GCOUNT", "g")] = c.execute_command("GCOUNT", "GET", "g")
        pre[("PNCOUNT", "p")] = c.execute_command("PNCOUNT", "GET", "p")
        assert pre[("GCOUNT", "g")] == gcount

        # phase B: keep traffic flowing and SIGKILL mid-stream — these
        # writes raced the kill, so the lattice may hold any prefix of
        # them; phase A must survive bit-exact
        try:
            for i in range(10_000):
                c.execute_command("GCOUNT", "INC", "g", 1)
                c.execute_command("TLOG", "INS", "burst", b"x%d" % i, i + 1)
                if i == 50:
                    proc.send_signal(signal.SIGKILL)
        except (ConnectionError, OSError):
            killed_mid_write = True
        proc.wait(timeout=30)
        assert killed_mid_write, "server outlived a SIGKILL mid-burst?"
    finally:
        if proc.poll() is None:
            stop_node(proc)
    assert not os.path.exists(os.path.join(data, "snapshot.jylis"))

    # restart: snapshot absent, peers nonexistent — journal or bust
    proc = spawn_node(port, cport, "jsoak", *extra)
    try:
        c = connect_client(port, proc=proc)
        deadline = time.time() + 30
        while time.time() < deadline:
            if c.execute_command("GCOUNT", "GET", "g") >= gcount:
                break
            time.sleep(0.2)
        post = {}
        for k in range(40):
            post[("TREG", "r%d" % k)] = c.execute_command("TREG", "GET", "r%d" % k)
            post[("TLOG", "l%d" % k)] = c.execute_command("TLOG", "GET", "l%d" % k)
            post[("UJSON", "f%d" % k)] = c.execute_command("UJSON", "GET", "d", "f%d" % k)
        for key, want in pre.items():
            if key[0] in ("GCOUNT", "PNCOUNT"):
                continue  # phase B raced these; checked monotone below
            assert post[key] == want, (key, post[key], want)
        # counters are monotone: >= the quiesced phase-A values, and the
        # phase-B prefix that flushed may push GCOUNT higher
        assert c.execute_command("GCOUNT", "GET", "g") >= gcount
        assert c.execute_command("PNCOUNT", "GET", "p") == pre[("PNCOUNT", "p")]
        replay = [
            line
            for line in c.execute_command("SYSTEM", "METRICS")
            if line.startswith(b"JOURNAL replayed_batches")
        ]
        assert replay and int(replay[0].rsplit(b" ", 1)[1]) > 0
    finally:
        stop_node(proc)


@pytest.mark.soak
@pytest.mark.slow  # nightly (`make soak`), not per-commit
def test_three_node_crash_drill(tmp_path):
    """The resilience story end to end, with REAL processes: a 3-node
    cluster takes writes; the seed node is SIGKILLed (no clean shutdown);
    the survivors keep serving and converging; the seed restarts from its
    ONLINE snapshot and bootstrap-syncs the writes it missed while dead;
    every node converges on everything."""
    import signal

    ports = [free_port() for _ in range(3)]
    cports = [free_port() for _ in range(3)]
    names = ["drill-a", "drill-b", "drill-c"]
    datas = [str(tmp_path / f"data{i}") for i in range(3)]
    seed_addr = f"127.0.0.1:{cports[0]}:{names[0]}"

    def boot(i):
        extra = ["--data-dir", datas[i], "--snapshot-interval", "0.3",
                 "--heartbeat-time", "0.2"]
        if i > 0:
            extra += ["--seed-addrs", seed_addr]
        return spawn_node(ports[i], cports[i], names[i], *extra)

    def until(fn, what, deadline_s=90):
        deadline = time.time() + deadline_s
        while time.time() < deadline:
            try:
                if fn():
                    return
            except Exception:
                pass
            time.sleep(0.25)
        raise AssertionError(f"timed out waiting for {what}")

    def read(port, *args):
        with Client("127.0.0.1", port, timeout=30) as c:
            return c.execute_command(*args)

    procs = [boot(i) for i in range(3)]
    try:
        clients = [connect_client(p, proc=pr) for p, pr in zip(ports, procs)]
        # phase 1: writes everywhere, cluster-wide convergence
        for i, c in enumerate(clients):
            assert c.execute_command("GCOUNT", "INC", "drill", i + 1) == b"OK"
        for p in ports:
            until(lambda p=p: read(p, "GCOUNT", "GET", "drill") == 6,
                  f"phase-1 convergence on :{p}")
        # wait until node 0's online snapshot cycles past these writes
        snap0 = os.path.join(datas[0], "snapshot.jylis")
        until(lambda: os.path.exists(snap0), "seed's online snapshot")
        first = os.path.getmtime(snap0)
        until(lambda: os.path.getmtime(snap0) != first, "snapshot cycle")

        # phase 2: SIGKILL the seed mid-cluster; survivors keep serving
        procs[0].send_signal(signal.SIGKILL)
        procs[0].wait(timeout=30)
        assert clients[1].execute_command("TLOG", "INS", "missed", "while-dead", 9) == b"OK"
        assert clients[2].execute_command("GCOUNT", "INC", "drill", 10) == b"OK"
        for p in ports[1:]:
            until(lambda p=p: read(p, "GCOUNT", "GET", "drill") == 16,
                  f"survivor convergence on :{p}")

        # phase 3: the seed restarts — online snapshot restores its own
        # pre-crash state, bootstrap sync fills in what it missed
        procs[0] = boot(0)
        c0 = connect_client(ports[0], proc=procs[0])
        until(lambda: c0.execute_command("GCOUNT", "GET", "drill") == 16,
              "restarted seed catches up the counter")
        until(lambda: c0.execute_command("TLOG", "GET", "missed")
              == [[b"while-dead", 9]], "restarted seed syncs the missed log")
        # and the whole cluster still agrees
        assert c0.execute_command("GCOUNT", "INC", "drill", 100) == b"OK"
        for p in ports:
            until(lambda p=p: read(p, "GCOUNT", "GET", "drill") == 116,
                  f"final convergence on :{p}")
    finally:
        for pr in procs:
            stop_node(pr)
