"""Bounded soak: a REAL node under sustained mixed churn.

Excluded from the per-commit suite (`-m soak` runs it; CI's nightly job
does). One spawned server takes ~30 seconds of continuous writes across
all five types — TREG overwrite churn (interner epoch compactions),
TLOG inserts with periodic TRIMs, counter increments, UJSON edits,
online snapshots every second — with spot reads checked against host
models throughout, and the process RSS must plateau: the memory at the
end may not grow more than 50% over the reading taken after the first
third (the interner-leak class of bug shows up here as monotonic
growth).
"""

from __future__ import annotations

import os
import time

import pytest

from procutil import free_port, connect_client, spawn_node, stop_node

SOAK_SECONDS = 30


def _rss_kb(pid: int) -> int:
    with open(f"/proc/{pid}/statm") as f:
        pages = int(f.read().split()[1])
    return pages * (os.sysconf("SC_PAGE_SIZE") // 1024)


@pytest.mark.soak
def test_thirty_second_mixed_churn_soak(tmp_path):
    port, cport = free_port(), free_port()
    data = str(tmp_path / "data")
    proc = spawn_node(
        port, cport, "soaknode",
        "--data-dir", data, "--snapshot-interval", "1",
    )
    try:
        c = connect_client(port, proc=proc)

        gcount = 0
        pn = 0
        treg: dict[int, tuple[bytes, int]] = {}
        tlog_n = 0
        rss_at_third = None
        start = time.time()
        i = 0
        while time.time() - start < SOAK_SECONDS:
            i += 1
            k = i % 500
            # TREG overwrite churn: every round replaces values, so the
            # interner must compact or RSS grows forever
            val = b"v%d-%d" % (k, i)
            assert c.execute_command("TREG", "SET", "r%d" % k, val, i) == b"OK"
            treg[k] = (val, i)
            assert c.execute_command("GCOUNT", "INC", "g", 1) == b"OK"
            gcount += 1
            assert c.execute_command("PNCOUNT", "DEC" if i % 3 else "INC", "p", 2) == b"OK"
            pn += 2 if i % 3 == 0 else -2
            assert c.execute_command("TLOG", "INS", "l", b"e%d" % i, i) == b"OK"
            tlog_n += 1
            if i % 400 == 0:
                assert c.execute_command("TLOG", "TRIM", "l", 50) == b"OK"
                tlog_n = 50
            if i % 7 == 0:
                assert c.execute_command(
                    "UJSON", "SET", "d", "f%d" % (i % 16), "%d" % i
                ) == b"OK"
            if i % 250 == 0:
                # spot reads against the host models
                assert c.execute_command("GCOUNT", "GET", "g") == gcount
                assert c.execute_command("PNCOUNT", "GET", "p") == pn
                want_val, want_ts = treg[k]
                assert c.execute_command("TREG", "GET", "r%d" % k) == [want_val, want_ts]
                size = c.execute_command("TLOG", "SIZE", "l")
                assert size == tlog_n, (size, tlog_n)
            if rss_at_third is None and time.time() - start > SOAK_SECONDS / 3:
                rss_at_third = _rss_kb(proc.pid)
        assert rss_at_third is not None, "soak too short to sample RSS"
        rss_end = _rss_kb(proc.pid)
        assert rss_end < rss_at_third * 1.5, (
            f"RSS grew {rss_at_third}kB -> {rss_end}kB during steady churn"
        )
        # final coherence + a live metrics read
        assert c.execute_command("GCOUNT", "GET", "g") == gcount
        metrics = c.execute_command("SYSTEM", "METRICS")
        assert any(line.startswith(b"TREG drains") for line in metrics)
    finally:
        stop_node(proc)
