"""Delta-interval replication (schema v8) pinning tests.

The contract (docs/replication.md, "Efficient State-based CRDTs by
Delta-Mutation", arXiv:1410.2803): every content-carrying delta batch
is sequenced per sender and kept in a bounded retransmit window;
receivers ack the cumulative contiguous seq; reconnection reships
EXACTLY the unacked window — and when the window can no longer replay
a peer's gap (cap eviction mid-partition), that peer is marked
interval-dirty and demoted to Merkle-range repair via MsgIntervalReset,
NEVER silently lost and NEVER a whole-state dump.
"""

import asyncio

import pytest

import jylis_tpu  # noqa: F401
from jylis_tpu import faults
from jylis_tpu.cluster import Cluster, codec
from jylis_tpu.cluster import cluster as cluster_mod
from jylis_tpu.cluster.cluster import _Conn, check_frame
from jylis_tpu.cluster.framing import FrameReader
from jylis_tpu.cluster.msg import (
    MsgDeltaAck,
    MsgIntervalReset,
    MsgRangeRequest,
    MsgSeqPush,
    MsgSyncDone,
)
from jylis_tpu.utils.address import Address

from test_cluster import TICK, Node, converge_wait, grab_ports, meshed, resp_call
from test_held_queue import _SinkWriter, _batch, _pushed_keys, _solo_cluster


def _msg_types(raw: bytes) -> list[str]:
    """Decode a recorded write stream into message type names."""
    frames = FrameReader()
    frames.append(bytes(raw))
    out = []
    for body in frames:
        checked = check_frame(body)
        assert checked is not None
        _origin_ms, payload = checked
        out.append(type(codec.decode(payload)).__name__)
    return out


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _attach_peer(cl, port="1", name="peer"):
    """Established active conn + its _PeerState, like a healthy mesh."""
    w = _SinkWriter()
    addr = Address("127.0.0.1", port, name)
    conn = _Conn(w, addr)
    conn.established = True
    cl._actives[addr] = conn
    st = cl._peers[addr] = cluster_mod._PeerState()
    return w, addr, conn, st


def test_broadcasts_are_sequenced_and_logged():
    cl = _solo_cluster()
    w, addr, conn, st = _attach_peer(cl)
    for key in (b"a", b"b", b"c"):
        cl.broadcast_deltas(_batch(key))
    assert cl._delta_seq == 3
    assert [seq for seq, _ in cl._delta_log] == [1, 2, 3]
    assert _pushed_keys(w.wrote) == [b"a", b"b", b"c"]
    # keepalives (content-free SYSTEM) are NOT sequenced
    cl.broadcast_deltas(("SYSTEM", [(b"_log", ([], 0))]))
    assert cl._delta_seq == 3
    assert len(cl._delta_log) == 3


def test_reconnect_reships_exactly_the_unacked_window():
    cl = _solo_cluster()
    w, addr, conn, st = _attach_peer(cl)
    for key in (b"a", b"b", b"c", b"d"):
        cl.broadcast_deltas(_batch(key))
    # the peer acked through seq 2, then its conn churned
    st.acked = 2
    w2 = _SinkWriter()
    conn2 = _Conn(w2, addr)
    conn2.established = True
    cl._actives[addr] = conn2
    cl._retransmit_unacked(conn2)
    assert _pushed_keys(w2.wrote) == [b"c", b"d"]
    assert cl._stats["deltas_reshipped"] == 2
    # the reshipped frames are stamped for rtt (acks will pop them)
    assert len(conn2.pong_sent) == 2


def test_no_ack_history_means_no_replay():
    """A brand-new peer bootstraps through the digest-tree sync; a
    1024-frame replay of history it was never owed would be waste."""
    cl = _solo_cluster()
    w, addr, conn, st = _attach_peer(cl)
    for key in (b"a", b"b"):
        cl.broadcast_deltas(_batch(key))
    st.acked = None
    w2 = _SinkWriter()
    conn2 = _Conn(w2, addr)
    conn2.established = True
    cl._actives[addr] = conn2
    cl._retransmit_unacked(conn2)
    assert w2.wrote == bytearray()
    assert cl._stats["deltas_reshipped"] == 0


def test_cap_eviction_marks_behind_peer_interval_dirty():
    """The satellite fix: held-window loss used to be a counter + warn;
    now cap eviction marks every behind peer dirty and announces the
    demotion the moment the peer is reachable."""
    cl = _solo_cluster()
    cl._delta_log_cap = 2
    w, addr, conn, st = _attach_peer(cl)
    st.acked = 1  # the peer saw seq 1 only
    for key in (b"a", b"b", b"c", b"d"):
        cl.broadcast_deltas(_batch(key))
    # window now holds [3, 4]; seqs 1-2 evicted past the peer's ack
    assert [seq for seq, _ in cl._delta_log] == [3, 4]
    assert st.interval_dirty
    assert cl.metrics_totals()["interval_dirty_peers"] == 1
    assert cl._stats["interval_resets_sent"] >= 1
    # the reset demotes optimistically: watermark jumps to the current
    # seq so retransmit never replays a window we declared unreplayable
    assert st.acked == cl._delta_seq


def test_gap_on_reconnect_sends_reset_not_partial_replay():
    cl = _solo_cluster()
    cl._delta_log_cap = 2
    w, addr, conn, st = _attach_peer(cl)
    for key in (b"a", b"b", b"c", b"d"):
        cl.broadcast_deltas(_batch(key))
    # peer acked 1, window starts at 3: the gap (seq 2) is unreplayable
    st.acked = 1
    w2 = _SinkWriter()
    conn2 = _Conn(w2, addr)
    conn2.established = True
    cl._actives[addr] = conn2
    cl._retransmit_unacked(conn2)
    assert st.interval_dirty
    assert cl._stats["interval_resets_sent"] == 1
    # exactly one frame left: the IntervalReset, no partial replay
    keys = _pushed_keys(w2.wrote)
    assert keys == []


def test_reestablishment_resends_a_possibly_lost_reset():
    """A MsgIntervalReset lost in flight (conn died first, injected
    send loss) must go out again on the next establishment even when no
    new writes advanced delta_seq — the idempotence guard's own
    bookkeeping (acked = reset_seq = delta_seq) would otherwise satisfy
    itself forever and strand the peer on its stale cursor."""
    cl = _solo_cluster()
    cl._delta_log_cap = 2
    w, addr, conn, st = _attach_peer(cl)
    for key in (b"a", b"b", b"c", b"d"):
        cl.broadcast_deltas(_batch(key))
    st.acked = 1  # gap fell off the window -> reset on reconnect
    w2 = _SinkWriter()
    conn2 = _Conn(w2, addr)
    conn2.established = True
    cl._actives[addr] = conn2
    cl._retransmit_unacked(conn2)
    assert _msg_types(w2.wrote) == ["MsgIntervalReset"]
    # the reset never arrived; the conn churns again with NO new writes
    w3 = _SinkWriter()
    conn3 = _Conn(w3, addr)
    conn3.established = True
    cl._actives[addr] = conn3
    cl._retransmit_unacked(conn3)
    assert _msg_types(w3.wrote) == ["MsgIntervalReset"]
    assert cl._stats["interval_resets_sent"] == 2


def test_oversized_replay_demotes_to_range_repair(monkeypatch):
    """The reconnection replay writes synchronously (no drain between
    frames): a window bigger than RETRANSMIT_BYTES_CAP must demote to
    range repair via MsgIntervalReset instead of blowing through the
    conn's write-buffer limit mid-replay and churning the redial."""
    cl = _solo_cluster()
    w, addr, conn, st = _attach_peer(cl)
    for key in (b"a", b"b", b"c"):
        cl.broadcast_deltas(_batch(key))
    st.acked = 1  # two frames pending
    monkeypatch.setattr(cluster_mod, "RETRANSMIT_BYTES_CAP", 1)
    w2 = _SinkWriter()
    conn2 = _Conn(w2, addr)
    conn2.established = True
    cl._actives[addr] = conn2
    cl._retransmit_unacked(conn2)
    assert st.interval_dirty
    assert cl._stats["interval_resets_sent"] == 1
    assert cl._stats["deltas_reshipped"] == 0
    # exactly one frame went out: the reset, never a partial replay
    assert _msg_types(w2.wrote) == ["MsgIntervalReset"]


def test_replay_skips_frames_the_held_flush_will_ship():
    """Frames still in the held queue reach a reconnecting peer through
    the upcoming held flush (strict FIFO): replaying them from the
    retransmit window too would ship every one twice and answer with
    duplicate acks."""
    cl = _solo_cluster()
    w, addr, conn, st = _attach_peer(cl)
    cl.broadcast_deltas(_batch(b"a"))
    cl.broadcast_deltas(_batch(b"b"))
    st.acked = 1  # peer acked a; b was sent but is still unacked
    # peer churns away: subsequent writes are held AND window-logged
    del cl._actives[addr]
    cl.broadcast_deltas(_batch(b"c"))
    cl.broadcast_deltas(_batch(b"d"))
    assert len(cl._held) == 2
    w2 = _SinkWriter()
    conn2 = _Conn(w2, addr)
    conn2.established = True
    cl._actives[addr] = conn2
    cl._retransmit_unacked(conn2)
    # only the non-held gap (b) replays; c/d ride the held flush once
    assert _pushed_keys(w2.wrote) == [b"b"]
    assert cl._stats["deltas_reshipped"] == 1
    cl._flush_held()
    assert _pushed_keys(w2.wrote) == [b"b", b"c", b"d"]
    assert not cl._held


def test_one_outstanding_range_request_per_conn():
    """The requester side of the repair budget: several mismatched
    types' tree tasks finishing together must not each start their own
    range stream — one MsgRangeRequest in flight per conn, the next
    round pulled only by the closing MsgSyncDone."""
    cl = _solo_cluster()
    w, addr, conn, st = _attach_peer(cl)
    conn.range_pending = {"GCOUNT": [0, 1], "PNCOUNT": [2]}
    cl._continue_ranges(conn)
    cl._continue_ranges(conn)  # a second tree task re-entering
    assert _msg_types(w.wrote).count("MsgRangeRequest") == 1
    # the round's SyncDone clears the flag and pulls the next type
    asyncio.run(cl._active_msg(conn, MsgSyncDone()))
    assert _msg_types(w.wrote).count("MsgRangeRequest") == 2
    assert not conn.range_pending
    # the walk is done: further SyncDones pull nothing
    asyncio.run(cl._active_msg(conn, MsgSyncDone()))
    assert _msg_types(w.wrote).count("MsgRangeRequest") == 2


def test_range_request_beyond_budget_is_served_in_full():
    """A requester with a bigger --range-budget than ours deletes the
    whole request from its pending cursor the moment it sends: serving
    only our budget's worth would strand the rest until the next
    periodic digest exchange. Over-budget requests stream in
    budget-sized sub-rounds, closed by exactly one MsgSyncDone."""
    cl = _solo_cluster()
    cl._range_budget = 2

    async def main():
        w = _SinkWriter()
        conn = _Conn(w, None)
        conn.established = True
        conn.peer_addr = Address("127.0.0.1", "9", "req")
        cl._passives.add(conn)
        await cl._passive_msg(conn, MsgRangeRequest("GCOUNT", (0, 1, 2, 3, 4)))
        for _ in range(200):
            if not cl._range_serve_inflight and not cl._range_queue:
                break
            await asyncio.sleep(0.01)
        assert not cl._range_queue
        return w

    w = asyncio.run(main())
    assert cl._stats["ranges_served"] == 5
    assert _msg_types(w.wrote).count("MsgSyncDone") == 1


def test_receiver_tracks_contiguity_and_acks_cumulative():
    cl = _solo_cluster()

    async def main():
        conn = _Conn(_SinkWriter(), None)
        conn.established = True
        conn.peer_addr = Address("127.0.0.1", "9", "sender")
        cl._passives.add(conn)
        skey = str(conn.peer_addr)
        # first contact baselines at the observed seq
        await cl._passive_msg(conn, MsgSeqPush(5, 5, "GCOUNT", ()))
        assert cl._recv_cum[skey] == 5
        # contiguous advance
        await cl._passive_msg(conn, MsgSeqPush(6, 6, "GCOUNT", ()))
        assert cl._recv_cum[skey] == 6
        # a gap parks out of order; cum holds
        await cl._passive_msg(conn, MsgSeqPush(8, 8, "GCOUNT", ()))
        assert cl._recv_cum[skey] == 6
        assert cl._recv_ooo[skey] == {8}
        # the retransmit fills the gap: park collapses
        await cl._passive_msg(conn, MsgSeqPush(7, 7, "GCOUNT", ()))
        assert cl._recv_cum[skey] == 8
        assert skey not in cl._recv_ooo
        # a duplicate below cum re-states the ack, cursor unchanged
        await cl._passive_msg(conn, MsgSeqPush(3, 3, "GCOUNT", ()))
        assert cl._recv_cum[skey] == 8

    asyncio.run(main())


def test_interval_reset_rebases_receiver_and_forces_repair():
    cl = _solo_cluster()

    async def main():
        conn = _Conn(_SinkWriter(), None)
        conn.established = True
        conn.peer_addr = Address("127.0.0.1", "9", "sender")
        cl._passives.add(conn)
        skey = str(conn.peer_addr)
        await cl._passive_msg(conn, MsgSeqPush(5, 5, "GCOUNT", ()))
        await cl._passive_msg(conn, MsgSeqPush(9, 9, "GCOUNT", ()))
        assert cl._recv_ooo[skey] == {9}
        cl._sync_req_tick[conn.peer_addr] = cl._tick  # cooldown armed
        await cl._passive_msg(conn, MsgIntervalReset(42))
        assert cl._recv_cum[skey] == 42
        assert skey not in cl._recv_ooo
        # the cooldown toward the sender is cleared: next contact pulls
        assert conn.peer_addr not in cl._sync_req_tick
        assert cl._stats["interval_resets_recv"] == 1

    asyncio.run(main())


def test_stale_incarnation_ack_triggers_rebase_reset():
    """A crash-rebooted sender restarts at seq 0 while receivers still
    hold its old (higher) cursor: their acks outrun the new counter,
    which must trigger a re-base reset — not a silently dead interval
    tier."""
    cl = _solo_cluster()
    w, addr, conn, st = _attach_peer(cl)
    cl.broadcast_deltas(_batch(b"a"))  # delta_seq == 1

    async def main():
        await cl._active_msg(conn, MsgDeltaAck(999))

    asyncio.run(main())
    assert cl._stats["interval_resets_sent"] == 1
    assert st.acked == cl._delta_seq  # re-based, not adopted


def test_blip_heals_by_retransmit_through_real_wire():
    """End to end: pushes silently dropped on the wire (injected send
    loss) heal on reconnection by exact retransmit — well inside one
    sync period, with the reshipped count visible in CLUSTER metrics."""

    async def main():
        p_a, p_b = grab_ports(2)
        a = Node("inta", p_a)
        b = Node("intb", p_b, seeds=[a.config.addr])
        await a.start()
        await b.start()
        try:
            assert await converge_wait(lambda: meshed(a, b), ticks=60)
            await asyncio.sleep(4 * TICK)  # establishment sync settles
            # healthy sequenced write first: B acks it, so A holds real
            # interval history for B (no ack history = no replay, by
            # design — bootstrap covers that case instead)
            assert await resp_call(a.server.port, b"GCOUNT INC warm 1\r\n")

            def b_acked():
                st = a.cluster._peers.get(b.config.addr)
                return st is not None and st.acked is not None

            assert await converge_wait(b_acked, ticks=60)
            # arm silent send loss on EVERY outbound cluster write (the
            # failpoint registry is process-global: a ~0.3 s two-way
            # blackout where every frame "succeeds" without arriving),
            # then write on A: B never sees the pushes, no acks advance
            faults.arm("cluster.write", "drop", None, None)
            for i in range(3):
                got = await resp_call(
                    a.server.port,
                    b"GCOUNT INC lost%d 7\r\n" % i,
                )
                assert got == b"+OK\r\n"
                await asyncio.sleep(2 * TICK)  # one flush window each
            faults.disarm("cluster.write")

            # force the conn churn that makes A re-establish and replay
            for conn in list(a.cluster._actives.values()):
                a.cluster._drop(conn)

            async def b_has_all():
                for i in range(3):
                    out = await resp_call(
                        b.server.port, b"GCOUNT GET lost%d\r\n" % i
                    )
                    if out != b":7\r\n":
                        return False
                return True

            deadline = asyncio.get_event_loop().time() + 60 * TICK
            ok = False
            while asyncio.get_event_loop().time() < deadline:
                if await b_has_all():
                    ok = True
                    break
                await asyncio.sleep(TICK)
            assert ok, "retransmit never healed the blip"
            assert a.cluster._stats["deltas_reshipped"] >= 1
        finally:
            await a.stop()
            await b.stop()

    asyncio.run(main())


def test_over_budget_partition_heals_range_repaired_never_full_dump():
    """The satellite acceptance: a partition that outlives the
    retransmit window must still end digest-matched — through the
    interval-dirty -> range-repair ladder, with the legacy whole-state
    dump counter pinned at ZERO on both nodes."""

    async def main():
        p_a, p_b = grab_ports(2)
        a = Node("ovra", p_a)
        b = Node("ovrb", p_b, seeds=[a.config.addr])
        a.cluster._delta_log_cap = 4  # make the window overrunnable
        await a.start()
        await b.start()
        try:
            assert await converge_wait(lambda: meshed(a, b), ticks=60)
            # healthy phase: one write replicates (B acks, so A holds
            # real interval history for B)
            assert await resp_call(a.server.port, b"GCOUNT INC warm 1\r\n")

            async def b_warm():
                out = await resp_call(b.server.port, b"GCOUNT GET warm\r\n")
                return out == b":1\r\n"

            deadline = asyncio.get_event_loop().time() + 60 * TICK
            while asyncio.get_event_loop().time() < deadline:
                if await b_warm():
                    break
                await asyncio.sleep(TICK)
            assert await b_warm()

            # partition: B's cluster stack goes away entirely
            b.cluster.dispose()
            await asyncio.sleep(2 * TICK)

            # writes far past the 4-batch window, one flush each
            for i in range(10):
                got = await resp_call(
                    a.server.port, b"GCOUNT INC part%d 3\r\n" % i
                )
                assert got == b"+OK\r\n", got
                await asyncio.sleep(2 * TICK)

            # the window overran B's watermark: B is interval-dirty
            def b_dirty():
                return (
                    a.cluster.metrics_totals()["interval_dirty_peers"] >= 1
                )

            assert await converge_wait(b_dirty, ticks=80), (
                a.cluster.metrics_totals()
            )

            # heal: B's cluster returns at the same address
            b.cluster = Cluster(b.config, b.database)
            await b.cluster.start()

            async def digests_match():
                da = await a.database.sync_digest_async()
                db_ = await b.database.sync_digest_async()
                return da == db_

            deadline = asyncio.get_event_loop().time() + 200 * TICK
            ok = False
            while asyncio.get_event_loop().time() < deadline:
                if await digests_match():
                    ok = True
                    break
                await asyncio.sleep(TICK)
            assert ok, "over-budget partition never digest-matched"
            # the acceptance bar: the heal went interval -> range, and
            # the legacy whole-state dump path NEVER fired
            assert a.cluster._stats["sync_full_dumps"] == 0
            assert b.cluster._stats["sync_full_dumps"] == 0
            assert (
                a.cluster._stats["ranges_served"] > 0
                or b.cluster._stats["ranges_requested"] > 0
            ), (a.cluster._stats, b.cluster._stats)

            # ... and the dirty flag clears once B's pull digest-matches
            def dirty_cleared():
                return (
                    a.cluster.metrics_totals()["interval_dirty_peers"] == 0
                )

            assert await converge_wait(
                dirty_cleared, ticks=3 * cluster_mod.SYNC_PERIOD_TICKS
            ), a.cluster.metrics_totals()
        finally:
            await a.stop()
            await b.stop()

    asyncio.run(main())
