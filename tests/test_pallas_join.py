"""Pallas fused dense join vs the XLA/oracle join — interpret mode on the
CPU harness (real-TPU compilation is exercised by bench.py --config
pallas-join)."""

import numpy as np
import pytest

import jylis_tpu  # noqa: F401
from jylis_tpu.ops import pallas_join, pncount


@pytest.mark.parametrize("seed", [0, 1])
def test_join_fused_matches_oracle(seed):
    rng = np.random.default_rng(seed)
    K, R = 1600, 32  # rows = K*R/128 = 400 = one BLOCK_ROWS tile
    state = pncount.from_counts(
        rng.integers(0, 1 << 60, (K, R)).astype(np.uint64),
        rng.integers(0, 1 << 60, (K, R)).astype(np.uint64),
    )
    deltas = pncount.from_counts(
        rng.integers(0, 1 << 60, (K, R)).astype(np.uint64),
        rng.integers(0, 1 << 60, (K, R)).astype(np.uint64),
    )
    assert pallas_join.supported(state)
    want = pncount.join(state, deltas)
    # join_fused donates its state arg: hand it a copy
    state2 = pncount.PNCountState(*(p.copy() for p in state))
    got = pallas_join.join_fused(state2, deltas, interpret=True)
    for w, g in zip(want, got):
        np.testing.assert_array_equal(np.asarray(w), np.asarray(g))


def test_supported_rejects_odd_shapes():
    st = pncount.init(100, 3)
    assert not pallas_join.supported(st)
