"""Shared helpers for tests that spawn REAL node processes.

The client-conformance, persistence crash-recovery, and soak modules
each grew their own copy of the free-port / spawn-command /
connect-retry plumbing; this is the one home for it. (scripts/smoke3.py
deliberately keeps its own spawn line: it boots nodes on the 8-device
virtual mesh to exercise sharded serving, not the plain CPU platform.)
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# spawn a node on the forced-CPU platform (the env pins JAX_PLATFORMS to
# the real chip; subprocesses must override it in-process)
SPAWN_CPU = (
    "import jax; jax.config.update('jax_platforms','cpu'); "
    "import sys; from jylis_tpu.main import main; main(sys.argv[1:])"
)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def spawn_node(port: int, cport: int, name: str, *extra: str) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-c", SPAWN_CPU, "--port", str(port), "--addr",
         f"127.0.0.1:{cport}:{name}", "--log-level", "warn", *extra],
        cwd=REPO,
    )


def connect_client(port: int, timeout_s: float = 120.0, proc=None):
    """jylis_tpu.client.Client to a node that may still be starting; fails
    fast if the process died."""
    from jylis_tpu.client import Client

    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if proc is not None and proc.poll() is not None:
            raise RuntimeError("node process died during startup")
        try:
            return Client("127.0.0.1", port, timeout=60)
        except OSError:
            time.sleep(0.3)
    raise RuntimeError(f"node on :{port} never came up")


def stop_node(proc: subprocess.Popen, grace: float = 60.0) -> None:
    proc.terminate()
    try:
        proc.wait(timeout=grace)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=10)
