"""jmodel: the schedule-replay corpus + bounded exploration tiers.

Tier-1 (per commit): every schedule file under ``tests/model/`` replays
with all invariants holding — the corpus accumulates one minimized
counterexample per fixed protocol defect (plus the schema-pinning
fixture), so a regression replays the exact interleaving that found the
bug. A small bounded exploration also runs per commit; the deep sweep
(bigger budgets, deeper frontier) rides ``-m soak``. ``make
model-smoke`` (scripts/jmodel --smoke) is the recorded-coverage gate
between the two.
"""

import glob
import json
import os

import pytest

from scripts.jmodel import MODEL_PERIODS, model_periods
from scripts.jmodel.explore import (
    SCHEDULE_SCHEMA,
    Explorer,
    minimize,
    replay_schedule,
    schedule_dict,
)
from scripts.jmodel.net import Link, Network, VirtualClock
from scripts.jmodel.world import CONFIG_NAMES, Violation, World

CORPUS = sorted(
    glob.glob(os.path.join(os.path.dirname(__file__), "model", "*.json"))
)


# ---- corpus ----------------------------------------------------------------


def test_corpus_exists_and_pins_schema():
    """The corpus directory ships with at least the schema fixture, and
    every committed schedule is a well-formed expect=pass regression."""
    assert CORPUS, "tests/model/ must hold at least the schema fixture"
    for path in CORPUS:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        assert data["schema"] == SCHEDULE_SCHEMA, path
        assert data["config"] in CONFIG_NAMES, path
        assert data["expect"] == "pass", (
            f"{path}: a committed schedule must expect 'pass' — an "
            "invariant name means an UNFIXED defect was committed"
        )
        assert isinstance(data["actions"], list) and data["actions"], path


@pytest.mark.parametrize(
    "path", CORPUS, ids=[os.path.basename(p) for p in CORPUS]
)
def test_corpus_replays_clean(path):
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    with model_periods():
        violation = replay_schedule(data)
    assert violation is None, (
        f"{os.path.basename(path)} regressed: {violation} — the defect "
        "this schedule pinned has come back"
    )


def test_replay_skips_actions_the_protocol_no_longer_enables():
    """A schedule referencing a conn that never exists degrades to a
    weaker test (skipped action), never a spurious failure — corpus
    files must survive protocol evolution."""
    sched = schedule_dict(
        "nodes2",
        [("deliver", "A>B#9", "fwd"), ("tick", "A"), ("quiesce",)],
    )
    with model_periods():
        assert replay_schedule(sched) is None


# ---- explorer machinery ----------------------------------------------------


def test_quick_exploration_holds_all_invariants():
    with model_periods():
        result = Explorer("nodes2", 3).run()
    assert result.violation is None, result.violation
    assert result.states > 50
    assert result.quiesced >= 1  # the first leaf always quiesces


def test_exploration_is_deterministic():
    with model_periods():
        a = Explorer("nodes3", 2).run()
        b = Explorer("nodes3", 2).run()
    assert (a.states, a.leaves) == (b.states, b.leaves)


# ---- BCOUNT escrow invariant (schema v9) -----------------------------------


def test_bcount_decrement_transfer_schedules_hold_invariant():
    """Exhaustive (bounded) exploration of concurrent BCOUNT decrement
    and escrow-transfer schedules: `0 <= value <= bound` holds on every
    replica's view in EVERY explored state. The bcount-focused budget
    zeros the structural-fault axes so the frontier is spent on the
    contention interplay: decs racing transfers racing delivery."""
    with model_periods():
        result = Explorer(
            "nodes2",
            6,
            budgets={
                "bdecs": 2, "bxfers": 1, "writes": 0, "kills": 0,
                "crashes": 0, "partitions": 0, "dups": 0,
            },
            max_states=30_000,
        ).run()
    assert result.violation is None, result.violation
    assert result.states > 500


def test_broken_escrow_rule_yields_minimized_counterexample():
    """Arm the DELIBERATELY broken escrow rule (decrement without the
    local rights check — world.py escrow_unsafe) and the explorer must
    find `value < 0`, minimize the schedule to the over-drawing
    decrements alone, and produce a standalone-replayable artifact.
    The same schedule replayed against the CORRECT rule holds every
    invariant — the escrow check is exactly what the bound rests on."""
    with model_periods():
        result = Explorer(
            "nodes2",
            5,
            budgets={"bdecs": 3, "bxfers": 1},
            max_states=20_000,
            escrow_unsafe=True,
        ).run()
        assert result.violation is not None
        assert result.violation["invariant"] == "bcount_negative"
        sched = result.schedule
        assert sched["escrow_unsafe"] is True
        # minimized to the decrement core: nothing structural survives
        assert all(a[0] == "bdec" for a in sched["actions"]), sched["actions"]
        assert len(sched["actions"]) == 3  # bound 2 + 1 overdraw
        # the artifact replays standalone to the SAME violation
        v = replay_schedule(json.loads(json.dumps(sched)))
        assert v is not None and v.name == "bcount_negative"
        # and the correct rule survives the identical schedule
        safe = {k: v2 for k, v2 in sched.items() if k != "escrow_unsafe"}
        assert replay_schedule(safe) is None


def test_bcount_transfer_funds_remote_decrements():
    """Directed schedule: the seed-escrow replica transfers a right to
    B; after delivery B's previously-refused decrement succeeds, and the
    quiesced world digest-matches with value within bounds."""
    from scripts.jmodel.world import BCOUNT_KEY

    with model_periods():
        world = World("nodes2", {"bdecs": 2, "bxfers": 1})
        try:
            db_a, db_b = world.dbs["A"], world.dbs["B"]
            # B holds no escrow yet: the local check refuses (OUTOFBOUND)
            assert not db_b.local_bdec()
            assert db_b.refused_decs == 1
            assert db_a.local_bxfer(db_b.rid)
            world.quiesce()  # ships the transfer, heals everything
            assert db_b.local_bdec(), "delivered escrow must fund the dec"
            world.quiesce()
            bc_a = db_a.state_b[BCOUNT_KEY]
            assert bc_a.value() == 1 and bc_a.bound() == 2
            assert len(set(world._digests().values())) == 1
        finally:
            world.close()


def test_lanes_world_bridges_and_converges():
    """The 2-lane config: a write on lane 1 reaches the external node E
    through the bus -> lane-0 bridge -> external mesh relay chain."""
    with model_periods():
        world = World("lanes2")
        try:
            world.apply(("write", "L1"))
            world.quiesce()
            digests = set(world._digests().values())
            assert len(digests) == 1
            # the seed writes + L1's extra write all visible everywhere
            assert world.dbs["E"].state == world.dbs["L1"].state
        finally:
            world.close()


def test_crash_reboot_recovers_local_writes_and_reconverges():
    with model_periods():
        world = World("nodes2")
        try:
            world.apply(("write", "A"))
            world.apply(("crash", "A"))
            # the journaled local writes survived the reboot (the seed
            # write on x, the extra write on the next cycled key)
            assert world.dbs["A"].state[b"x"][1] == 1
            assert world.dbs["A"].state[b"y"][1] == 1
            world.quiesce()
            assert len(set(world._digests().values())) == 1
        finally:
            world.close()


# ---- sessions & regions (schema v10) ---------------------------------------


def test_regions_world_prunes_to_sparse_topology_and_relays():
    """The regions3 config: after quiescence the topology is the policy
    one (bar<->baz never peered — their traffic transits foo's
    origin-preserving relays), every replica digest-matches, and every
    minted token is dominated everywhere (the quiesce session law)."""
    with model_periods():
        world = World("regions3")
        try:
            world.apply(("write", "bar"))
            world.apply(("mint", "bar"))
            world.quiesce()
            assert len(set(world._digests().values())) == 1
            bar = world.instances["bar"].cluster
            baz = world.instances["baz"].cluster
            bar_addr = str(world.instances["bar"].addr)
            baz_addr = str(world.instances["baz"].addr)
            assert baz_addr not in {str(a) for a in bar._actives}
            assert bar_addr not in {str(a) for a in baz._actives}
            # the relay chain actually carried traffic
            foo = world.instances["foo"].cluster
            assert foo._stats["relays_sent"] > 0
            # bar's token verifies on baz: the cross-region session path
            _g, vec, floor, _b = world.tokens[0]
            svec = world.dbs["baz"].sessions.vector()
            assert all(svec.get(r, 0) >= s for r, s in vec.items())
            assert world.dbs["baz"].state[b"y"][2] >= 1  # bar's write
        finally:
            world.close()


def test_session_exploration_holds_ryw_in_every_config():
    """Bounded exploration with a mint in every group: the session_ryw
    invariant (a token-satisfied read never observes a regression) and
    the quiescence domination law hold across every explored schedule
    of the regions and lane-bus configs."""
    for config in ("regions3", "lanes2"):
        with model_periods():
            result = Explorer(config, 3, quiesce_every=8).run()
        assert result.violation is None, (config, result.violation)
        assert result.states > 200, (config, result.states)


def _drive_session_break(session_unsafe: bool):
    """Directed schedule for the broken-watermark demonstration: A's
    seed write reaches B, A mints, B crash-reboots (losing A's column —
    remote state is not journaled), A writes again and only the NEW seq
    reaches the rebooted B (its rejoin sync is held back). The unsafe
    watermark rule jumps over the gap and B falsely satisfies A's
    token; the safe rule parks the seq and stays honestly STALE."""
    with model_periods():
        w = World("nodes2", session_unsafe=session_unsafe)
        trace: list = []

        def do(a):
            trace.append(tuple(a))
            if w.apply(a):
                w.check_invariants()

        def pump():
            # deliver ONLY the A-dialed conn's frames: B's own rejoin
            # sync stays in flight, so the x column is still missing
            # when the post-crash seq push arrives
            for _ in range(4):
                for a in list(w.enabled_actions()):
                    if a[0] == "deliver" and a[1].startswith("A>"):
                        do(a)

        try:
            do(("tick", "A"))
            pump()
            do(("tick", "A"))
            pump()
            do(("mint", "A"))
            do(("crash", "B"))
            do(("write", "A"))
            for _ in range(6):
                do(("tick", "A"))
                pump()
            return None, trace
        except Violation as v:
            return v, trace
        finally:
            w.close()


def test_broken_session_watermark_yields_minimized_counterexample():
    """Arm the DELIBERATELY broken session-watermark rule (first-
    observed jump — sessions.SessionIndex unsafe mode) and the directed
    schedule must produce a token-satisfied read missing the token's
    write (session_ryw); ddmin shrinks it to a standalone-replayable
    artifact, and the SAME schedule against the correct contiguity rule
    holds every invariant — the strict watermark is exactly what
    read-your-writes rests on."""
    v, trace = _drive_session_break(session_unsafe=True)
    assert v is not None and v.name == "session_ryw", v
    with model_periods():
        minimized = minimize(
            "nodes2", trace, "session_ryw", session_unsafe=True
        )
        sched = schedule_dict(
            "nodes2", minimized, expect="session_ryw",
            note=v.detail, session_unsafe=True,
        )
        assert sched["session_unsafe"] is True
        assert len(minimized) < len(trace)
        replayed = replay_schedule(json.loads(json.dumps(sched)))
        assert replayed is not None and replayed.name == "session_ryw"
        # the correct rule survives the identical schedule
        safe = {k: v2 for k, v2 in sched.items() if k != "session_unsafe"}
        assert replay_schedule(safe) is None


def test_safe_session_rule_survives_the_directed_schedule():
    v, _trace = _drive_session_break(session_unsafe=False)
    assert v is None, v


def test_minimizer_shrinks_to_the_failing_core(monkeypatch):
    from scripts.jmodel import explore

    def fake_replay(data, budgets=None, runtime=None):
        acts = [tuple(a) for a in data["actions"]]
        if ("tick", "A") in acts and ("tick", "B") in acts:
            return Violation("fake", "both ticks present")
        return None

    monkeypatch.setattr(explore, "replay_schedule", fake_replay)
    out = minimize(
        "nodes2",
        [("tick", "A"), ("write", "A"), ("tick", "B"), ("write", "B")],
        "fake",
    )
    assert out == [("tick", "A"), ("tick", "B")]


def test_model_periods_patch_is_scoped():
    from jylis_tpu.cluster import cluster as cluster_mod

    before = cluster_mod.SYNC_PERIOD_TICKS
    with model_periods():
        assert cluster_mod.SYNC_PERIOD_TICKS == (
            MODEL_PERIODS["SYNC_PERIOD_TICKS"]
        )
    assert cluster_mod.SYNC_PERIOD_TICKS == before


# ---- model network semantics ----------------------------------------------


def test_virtual_clock_is_explorer_driven():
    clock = VirtualClock()
    t0 = clock.now_ms()
    assert clock.now_ms() == t0  # never advances on its own
    clock.advance(250)
    assert clock.now_ms() == t0 + 250
    assert clock.perf() < clock.perf()  # strictly increasing stamps


def test_link_kill_discards_in_flight_frames():
    net = Network()
    link = Link("t/fwd", net)
    link.write(b"frame1")
    link.deliver_one()
    link.write(b"frame2")
    link.kill()
    assert link.eof
    assert not link.outbox and not link.inbox  # torn-down socket = loss


# ---- the deep sweep (nightly) ----------------------------------------------


@pytest.mark.soak
@pytest.mark.slow  # nightly (`make soak`), not per-commit — every soak
# test carries both marks so tier-1's `-m 'not slow'` override (which
# replaces the addopts soak filter) still skips it; the v8 state space
# is ~2x the v7 one, which pushed these cells well past the tier-1 box
@pytest.mark.parametrize(
    "config,depth",
    [("nodes2", 8), ("nodes3", 6), ("lanes2", 6), ("regions3", 6)],
)
def test_soak_deep_exploration(config, depth):
    """Bigger budgets (two kills / dups / crashes), deeper frontier,
    denser quiescence sampling — bounded by max_states so the nightly
    stays finite."""
    with model_periods():
        result = Explorer(
            config,
            depth,
            budgets={"kills": 2, "dups": 2, "crashes": 2},
            quiesce_every=32,
            max_states=60_000,
        ).run()
    assert result.violation is None, result.violation
    assert result.states > 1_000


def test_bridge_tokens_verify_live_despite_interleaved_relays():
    """Review-find regression: a bridge's stream interleaves its own
    SeqPush with RelayPush frames; receivers must advance the bridge's
    OWN watermark on BOTH (contiguous transport application covers
    every own-write frame below), or one relayed frame parks the
    bridge's next own seq forever and its tokens go STALE on the LIVE
    path. Adoption masks the bug wherever a digest sync fires, so this
    test runs at PRODUCTION periods (no model_periods shrink): the
    whole window stays under one SYNC_PERIOD, the only adoption is the
    establishment-time sync (before the minted seqs exist), and the
    assertion exercises pure contiguous application."""
    w = World("regions3")
    try:
        def pump(rounds: int):
            for _ in range(rounds):
                for key in sorted(w.instances):
                    if w.instances[key].alive:
                        w.apply(("tick", key))
                for _ in range(4):
                    for a in list(w.enabled_actions()):
                        if a[0] == "deliver":
                            w.apply(a)

        pump(8)  # establish + seed writes + relays flowing
        # bar's seed write has crossed foo's relay into baz by now;
        # foo's stream therefore carries RelayPush frames. Mint at
        # foo AFTER a fresh foo write: its token references foo
        # seqs ABOVE the relay frames.
        w.apply(("write", "foo"))
        w.apply(("mint", "foo"))
        g, vec, floor, _boot = w.tokens[-1]
        assert g == "foo"
        foo_srid = w.instances["foo"].cluster._srid
        assert vec.get(foo_srid, 0) > 0, vec
        # foo relayed at least one foreign batch below the minted seq
        assert w.instances["foo"].cluster._stats["relays_sent"] > 0
        pump(8)  # live delivery only — total ticks < SYNC_PERIOD_TICKS
        for group in ("bar", "baz"):
            svec = w.dbs[group].sessions.vector()
            assert all(
                svec.get(r, 0) >= s for r, s in vec.items()
            ), (group, svec, vec)
    finally:
        w.close()


# ---- bridge failover (PR 15) ------------------------------------------------


def _drive_bridge_break(bridge_unsafe: bool):
    """Directed schedule for the broken-demotion demonstration: foo is
    region ra's bridge (foo+bar; baz is rb). Mesh up so bar holds
    received-frame evidence of foo, bkill foo (down and STAYS down —
    the new axis), then keep ticking bar: its evidence of foo ages
    past the model demotion bound while bar itself is a live
    successor. The broken rule (never demote — the pre-failover v10
    behavior) keeps electing the dead bridge, which the
    bridge_demotion invariant flags; the safe rule hands over to bar
    on the same schedule."""
    from scripts.jmodel.world import BRIDGE_DEMOTE_MODEL

    with model_periods():
        w = World("regions3", bridge_unsafe=bridge_unsafe)
        trace: list = []

        def do(a):
            trace.append(tuple(a))
            if w.apply(a):
                w.check_invariants()

        def pump():
            for _ in range(4):
                for a in list(w.enabled_actions()):
                    if a[0] == "deliver":
                        do(a)

        try:
            for _ in range(3):
                for key in ("foo", "bar", "baz"):
                    do(("tick", key))
                pump()
            # bar must hold direct evidence of foo before the kill, or
            # demotion has nothing to age out
            assert (
                str(w.instances["foo"].addr)
                in w.instances["bar"].cluster._seen_tick
            )
            do(("bkill", "foo"))
            for _ in range(BRIDGE_DEMOTE_MODEL + 3):
                do(("tick", "bar"))
            return None, trace
        except Violation as v:
            return v, trace
        finally:
            w.close()


def test_broken_demotion_rule_yields_minimized_counterexample():
    """Arm the DELIBERATELY broken bridge-demotion rule (an
    unreachable threshold — exactly the v10 single-WAN-path status
    quo) and the directed schedule must keep a provably-dead bridge
    elected past the bound with a live successor available
    (bridge_demotion); ddmin shrinks it to a standalone-replayable
    artifact, and the SAME schedule under the real liveness rule holds
    every invariant — bounded handover is exactly what the demotion
    threshold buys."""
    v, trace = _drive_bridge_break(bridge_unsafe=True)
    assert v is not None and v.name == "bridge_demotion", v
    with model_periods():
        minimized = minimize(
            "regions3", trace, "bridge_demotion", bridge_unsafe=True
        )
        sched = schedule_dict(
            "regions3", minimized, expect="bridge_demotion",
            note=v.detail, bridge_unsafe=True,
        )
        assert sched["bridge_unsafe"] is True
        assert len(minimized) < len(trace)
        replayed = replay_schedule(json.loads(json.dumps(sched)))
        assert replayed is not None and replayed.name == "bridge_demotion"
        # the liveness rule survives the identical schedule (and its
        # final auto-quiesce reboots the killed bridge and converges)
        safe = {k: v2 for k, v2 in sched.items() if k != "bridge_unsafe"}
        assert replay_schedule(safe) is None


def test_safe_demotion_rule_survives_the_directed_schedule():
    v, _trace = _drive_bridge_break(bridge_unsafe=False)
    assert v is None, v


def test_bkill_window_explores_and_quiesce_reboots():
    """The bkill/breboot axis end to end: kill the bridge, let the
    survivors churn through the succession window, reboot, and the
    world still quiesces to a digest match with every ladder law
    holding (zero whole-state dumps is the real cluster's gate; here
    the model's convergence + drain laws are the proof)."""
    with model_periods():
        w = World("regions3")
        try:
            def pump(rounds: int):
                for _ in range(rounds):
                    for key in sorted(w.instances):
                        if w.instances[key].alive:
                            w.apply(("tick", key))
                    for _ in range(4):
                        for a in list(w.enabled_actions()):
                            if a[0] == "deliver":
                                w.apply(a)
                    w.check_invariants()

            pump(4)
            # baseline BEFORE the kill: bootstrap already counted the
            # self -> foo reclassification on bar
            h0 = w.instances["bar"].cluster._stats["bridge_handovers"]
            assert w.apply(("bkill", "foo"))
            assert not w._group_alive("foo")
            w.check_invariants()
            pump(8)  # the succession window: bar takes over ra
            bar = w.instances["bar"].cluster
            assert bar._bridge_of("ra") == str(w.instances["bar"].addr)
            assert bar._stats["bridge_handovers"] > h0
            assert w.apply(("breboot", "foo"))
            pump(4)
            w.quiesce()  # digest match + drained ladders everywhere
        finally:
            w.close()
