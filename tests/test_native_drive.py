"""Deliberately jax-free drive of the native serving engine — the
`make sanitize` vehicle.

The ASAN+UBSAN build (`make sanitize`) runs this module (plus the RESP
scanner differentials in test_native_resp.py) with the sanitizer runtime
LD_PRELOADed; jax cannot be imported there (jaxlib's pybind11 C++
exceptions abort under the ASAN interceptor), so everything here drives
``ServeEngine`` via ctypes only: full pipelined bursts through
``scan_apply`` over all five types, the reply-buffer flush (rc 2) and
defer (rc 1) boundaries, protocol errors, the UJSON render memo and
write queue, TLOG interner compaction, and the bulk delta exports. In
the regular suite it doubles as an engine integration test.

Keep this module importable without jax: no jylis_tpu.models /
jylis_tpu.ops imports.
"""

from __future__ import annotations

import pytest

from jylis_tpu.native import lib
from jylis_tpu.native.engine import ServeEngine


@pytest.fixture
def eng() -> ServeEngine:
    cdll = lib()
    assert cdll is not None, "native library must build in this environment"
    return ServeEngine(cdll)


def resp(*args: bytes) -> bytes:
    out = b"*%d\r\n" % len(args)
    for a in args:
        out += b"$%d\r\n%s\r\n" % (len(a), a)
    return out


def drain_native(eng, burst: bytes):
    """Feed a whole burst; collect replies and deferred commands until
    the engine stops (rc 0/-1/-2)."""
    buf = bytearray(burst)
    replies = b""
    deferred = []
    rc = 0
    while True:
        rc, consumed, out, unhandled, _changed = eng.scan_apply(buf)
        replies += out
        del buf[:consumed]
        if rc == 1:
            deferred.append(unhandled)
            continue
        if rc == 2:
            continue
        return rc, replies, deferred, bytes(buf)


def test_counter_burst_and_reply_order(eng):
    burst = (
        resp(b"GCOUNT", b"INC", b"k", b"5")
        + resp(b"GCOUNT", b"GET", b"k")
        + resp(b"PNCOUNT", b"INC", b"k", b"9")
        + resp(b"PNCOUNT", b"DEC", b"k", b"11")
        + resp(b"PNCOUNT", b"GET", b"k")
        + resp(b"GCOUNT", b"GET", b"nope")
    )
    rc, replies, deferred, rest = drain_native(eng, burst)
    assert (rc, rest) == (0, b"")
    assert not deferred
    assert replies == b"+OK\r\n:5\r\n+OK\r\n+OK\r\n:-2\r\n:0\r\n"
    served = eng.served_counts()
    assert served["GCOUNT"] == 3 and served["PNCOUNT"] == 3


def test_gcount_dec_is_not_native(eng):
    """GCOUNT has no DEC: the command must defer to the Python oracle
    (which renders the help text) — parity manifest territory."""
    rc, replies, deferred, _ = drain_native(
        eng, resp(b"GCOUNT", b"DEC", b"k", b"1")
    )
    assert rc == 0 and replies == b""
    assert deferred == [[b"GCOUNT", b"DEC", b"k", b"1"]]


def test_treg_set_get_and_big_value_rc2(eng):
    rc, replies, deferred, _ = drain_native(
        eng,
        resp(b"TREG", b"SET", b"r", b"hello", b"7")
        + resp(b"TREG", b"GET", b"r")
        + resp(b"TREG", b"GET", b"missing"),
    )
    assert rc == 0 and not deferred
    assert replies == b"+OK\r\n*2\r\n$5\r\nhello\r\n:7\r\n$-1\r\n"

    # a value larger than the 64 KiB reply buffer: SET banks it fine,
    # GET alone outgrows the buffer -> defers to Python (rc 1); with a
    # small reply already buffered the engine first asks for a flush
    # (rc 2) and THEN defers — both paths covered by drain_native
    big = b"v" * (1 << 17)
    rc, replies, deferred, _ = drain_native(
        eng,
        resp(b"TREG", b"SET", b"big", big, b"9")
        + resp(b"GCOUNT", b"INC", b"pad", b"1")
        + resp(b"TREG", b"GET", b"big"),
    )
    assert rc == 0
    assert replies == b"+OK\r\n+OK\r\n"
    assert deferred == [[b"TREG", b"GET", b"big"]]


def test_treg_lww_winner_rule(eng):
    burst = (
        resp(b"TREG", b"SET", b"r", b"aa", b"5")
        + resp(b"TREG", b"SET", b"r", b"zz", b"5")  # same ts: value wins
        + resp(b"TREG", b"SET", b"r", b"old", b"4")  # older ts: loses
        + resp(b"TREG", b"GET", b"r")
    )
    rc, replies, _, _ = drain_native(eng, burst)
    assert rc == 0
    assert replies.endswith(b"*2\r\n$2\r\nzz\r\n:5\r\n")


def test_tlog_ins_size_get_cutoff(eng):
    burst = (
        resp(b"TLOG", b"INS", b"l", b"e1", b"10")
        + resp(b"TLOG", b"INS", b"l", b"e2", b"20")
        + resp(b"TLOG", b"INS", b"l", b"e2", b"20")  # dup: merged view dedups
        + resp(b"TLOG", b"SIZE", b"l")
        + resp(b"TLOG", b"GET", b"l")
        + resp(b"TLOG", b"GET", b"l", b"1")
        + resp(b"TLOG", b"CUTOFF", b"l")
        + resp(b"TLOG", b"SIZE", b"missing")
        + resp(b"TLOG", b"GET", b"missing")
    )
    rc, replies, deferred, _ = drain_native(eng, burst)
    assert rc == 0 and not deferred
    assert replies == (
        b"+OK\r\n+OK\r\n+OK\r\n:2\r\n"
        b"*2\r\n*2\r\n$2\r\ne2\r\n:20\r\n*2\r\n$2\r\ne1\r\n:10\r\n"
        b"*1\r\n*2\r\n$2\r\ne2\r\n:20\r\n"
        b":0\r\n:0\r\n*0\r\n"
    )
    # TRIM dispatches a device drain: never native
    rc, _, deferred, _ = drain_native(eng, resp(b"TLOG", b"TRIM", b"l", b"1"))
    assert deferred == [[b"TLOG", b"TRIM", b"l", b"1"]]


def test_tlog_interner_compaction_remaps_live_vids(eng):
    # intern far more values than the compaction floor, then converge
    # the rows away so most become garbage
    row = eng.tlog_upsert(b"l")
    eng.tlog_ins(row, 1000, b"val-0")
    # build the merged-view memo now (a SIZE does it); subsequent ins
    # calls then maintain it, so the drain below carries a valid base
    assert eng.tlog_size(row) == 1
    for i in range(1, 9000):
        eng.tlog_ins(row, 1000 + i, b"val-%d" % i)  # ts 1000..9999
    eng.tlog_flush_deltas()  # drop the delta accumulator's references
    # a drain that trimmed to cutoff 9998 keeps exactly ts 9998, 9999:
    # the memo is current (ins maintains it), so the carried base is the
    # filtered memo and stays valid
    eng.tlog_finish_row(row, 2, 9998)
    eng.tlog_finish_end()
    assert eng.tlog_compact() in (True, False)
    # force: repeat until the floor logic actually compacts or stabilises
    for _ in range(3):
        if eng.tlog_compact():
            break
    size = eng.tlog_size(row)
    assert size == eng.tlog_len_cache(row)
    # the carried base must still resolve through the remapped interner
    ents = eng.tlog_merged_entries(row)
    assert ents is not None and len(ents) == size
    for ts, val in ents:
        assert val.startswith(b"val-")


def test_ujson_validate_bank_and_memo(eng):
    # valid writes bank natively (+OK), invalid ones defer to the oracle
    burst = (
        resp(b"UJSON", b"INS", b"d", b"tags", b'"x"')
        + resp(b"UJSON", b"SET", b"d", b"obj", b'{"a": [1, 2.5e3, null]}')
        + resp(b"UJSON", b"RM", b"d", b"tags", b'"x"')
        + resp(b"UJSON", b"CLR", b"d", b"obj")
        + resp(b"UJSON", b"INS", b"d", b"bad", b"{not json}")
        + resp(b"UJSON", b"INS", b"d", b"ctl", b'"a\x01b"')
        + resp(b"UJSON", b"SET", b"d", b"deep", b"[" * 100 + b"]" * 100)
    )
    rc, replies, deferred, _ = drain_native(eng, burst)
    assert rc == 0
    assert replies == b"+OK\r\n" * 4
    assert [d[1] for d in deferred] == [b"INS", b"INS", b"SET"]
    banked = eng.uq_drain()
    assert [b[0] for b in banked] == [b"INS", b"SET", b"RM", b"CLR"]
    assert banked[0] == [b"INS", b"d", b"tags", b'"x"']
    assert eng.uq_count() == 0

    # GET misses defer; after the oracle installs a render, it serves
    # natively; an overlapping write invalidates exactly the prefix
    rc, _, deferred, _ = drain_native(eng, resp(b"UJSON", b"GET", b"d"))
    assert deferred == [[b"UJSON", b"GET", b"d"]]
    eng.uj_memo_put(b"d", [], b"$9\r\n{\"a\":123}\r\n")
    eng.uj_memo_put(b"d", [b"a"], b"$3\r\n123\r\n")
    rc, replies, deferred, _ = drain_native(
        eng, resp(b"UJSON", b"GET", b"d") + resp(b"UJSON", b"GET", b"d", b"a")
    )
    assert rc == 0 and not deferred
    assert replies == b"$9\r\n{\"a\":123}\r\n$3\r\n123\r\n"
    assert eng.uj_memo_len(b"d") == 2
    # INS under a.b invalidates the renders at prefixes "" and "a"
    rc, replies, _, _ = drain_native(
        eng, resp(b"UJSON", b"INS", b"d", b"a", b"b", b"1")
    )
    assert replies == b"+OK\r\n"
    assert eng.uj_memo_len(b"d") == 0


def test_ujson_utf8_path_gate(eng):
    # invalid UTF-8 in a path component defers (the memo key must be
    # canonical bytes); valid raw UTF-8 banks natively
    rc, replies, deferred, _ = drain_native(
        eng,
        resp(b"UJSON", b"INS", b"d", b"\xff\xfe", b"1")
        + resp(b"UJSON", b"INS", b"d", "café".encode(), b"2"),
    )
    assert rc == 0
    assert replies == b"+OK\r\n"
    assert deferred == [[b"UJSON", b"INS", b"d", b"\xff\xfe", b"1"]]


def test_protocol_error_and_oversized_command(eng):
    rc, replies, deferred, rest = drain_native(
        eng, resp(b"GCOUNT", b"INC", b"k", b"1") + b"*1\r\n$bogus\r\n"
    )
    assert rc == -1
    assert replies == b"+OK\r\n"
    # an arg-count overflow reports rc -2 (caller grows and demotes)
    many = resp(*([b"GCOUNT", b"GET"] + [b"k"] * 2000))
    rc, _, _, _ = drain_native(eng, many)
    assert rc == -2


def test_split_burst_resumes_mid_command(eng):
    whole = resp(b"GCOUNT", b"INC", b"k", b"3") + resp(b"GCOUNT", b"GET", b"k")
    for cut in (1, 7, len(whole) // 2, len(whole) - 2):
        e = ServeEngine(lib())
        buf = bytearray(whole[:cut])
        rc, consumed, out, _, _ = e.scan_apply(buf)
        assert rc == 0
        del buf[:consumed]
        buf += whole[cut:]
        rc, consumed, out2, _, _ = e.scan_apply(buf)
        assert rc == 0
        assert (out + out2) == b"+OK\r\n:3\r\n"


def test_bulk_delta_exports(eng):
    rc, _, _, _ = drain_native(
        eng,
        resp(b"TREG", b"SET", b"r1", b"v1", b"1")
        + resp(b"TREG", b"SET", b"r2", b"v2", b"2")
        + resp(b"TLOG", b"INS", b"l1", b"e", b"5"),
    )
    assert rc == 0
    treg = eng.treg_flush_deltas()
    assert treg == [(b"r1", (b"v1", 1)), (b"r2", (b"v2", 2))]
    tlog = eng.tlog_flush_deltas()
    assert tlog == [(b"l1", ([(b"e", 5)], 0))]
    # cleared: a second flush exports nothing
    assert eng.treg_flush_deltas() == []
    assert eng.tlog_flush_deltas() == []
