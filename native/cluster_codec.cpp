// Native fast path for the cluster wire codec's MsgPushDeltas hot loop.
//
// Reference analog: /root/reference/jylis/_serialise.pony:3-14 — the
// reference's message serialiser is compiled Pony; this is the rebuild's
// compiled equivalent for the anti-entropy broadcast/converge path, where
// per-key deltas would otherwise be varint-packed in a Python loop
// (jylis_tpu/cluster/codec.py is the always-available semantic oracle;
// output here must be byte-identical for every input this file accepts).
//
// Wire format (schema v1, see codec.py _SCHEMA_TEXT): LEB128 varints,
// varint-length-prefixed byte strings, tag 0x03 = PushDeltas followed by
// name, batch count, then per key: key bytes + a per-type delta payload.
//
// The Python wrapper (jylis_tpu/native/codec.py) flattens delta objects to
// contiguous arrays (one pass), and this file does all byte-level work in
// one FFI call per message. Decode is two-pass: measure (counts) then fill
// (slices + values); both passes are memory-speed walks.
//
// Return conventions: encode -> bytes written, or -1 (buffer too small /
// unencodable). measure/decode -> 0 ok, -1 malformed, -2 unsupported here
// (caller falls back to the Python oracle, e.g. varints past 64 bits).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <utility>
#include <vector>

namespace {

struct Writer {
  uint8_t* p;
  uint8_t* end;
  bool ok = true;

  void u8(uint8_t b) {
    if (p < end) {
      *p++ = b;
    } else {
      ok = false;
    }
  }
  void varint(uint64_t v) {
    while (true) {
      uint8_t b = v & 0x7f;
      v >>= 7;
      if (v) {
        u8(b | 0x80);
      } else {
        u8(b);
        return;
      }
    }
  }
  void bytes(const uint8_t* b, int64_t n) {
    varint(static_cast<uint64_t>(n));
    if (end - p >= n) {
      memcpy(p, b, static_cast<size_t>(n));
      p += n;
    } else {
      ok = false;
    }
  }
};

struct Reader {
  const uint8_t* base;
  const uint8_t* p;
  const uint8_t* end;
  int rc = 0;  // sticky: 0 ok, -1 malformed, -2 unsupported

  // Mirrors codec.py _Reader.varint: accepts up to shift 70, but any
  // value that does not fit in 64 bits is out of this fast path's domain
  // (the oracle would produce a Python bigint) -> rc -2.
  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (true) {
      if (p >= end) {
        rc = rc ? rc : -1;
        return 0;
      }
      uint8_t b = *p++;
      if (shift >= 64 && (b & 0x7f)) {
        rc = rc ? rc : -2;
        return 0;
      }
      if (shift == 63 && (b & 0x7e)) {
        rc = rc ? rc : -2;
        return 0;
      }
      if (shift < 64) v |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
      if (shift > 70) {
        rc = rc ? rc : -1;
        return 0;
      }
    }
  }
  // An item count (keys, dict entries, log entries). Every counted item
  // consumes at least one byte, so any count exceeding the remaining
  // buffer guarantees the oracle raises "truncated" before finishing —
  // and bounding here keeps the count a sane non-negative int64 (a raw
  // 2^64-1 varint would cast to a NEGATIVE int64, silently skip the
  // entry loop the oracle still walks, and desync measure from decode).
  int64_t count() {
    uint64_t v = varint();
    if (rc) return 0;
    if (v > static_cast<uint64_t>(end - p)) {
      rc = -1;
      return 0;
    }
    return static_cast<int64_t>(v);
  }
  // A length-prefixed byte string; returns its offset from base.
  int64_t bytes(int64_t* len_out) {
    uint64_t n = varint();
    if (rc) return 0;
    if (static_cast<uint64_t>(end - p) < n) {
      rc = -1;
      return 0;
    }
    int64_t off = p - base;
    p += n;
    *len_out = static_cast<int64_t>(n);
    return off;
  }
  bool done() const { return rc == 0 && p == end; }
};

}  // namespace

extern "C" {

// ---- counters: GCOUNT (ndicts=1) / PNCOUNT (ndicts=2) ----------------------
// delta/GCOUNT = [(rid:varint v:varint)]; PNCOUNT = two such dicts.
// rids/vals are flattened in key-major order; dict entries must already be
// in the oracle's canonical order (sorted by rid) for byte-identity.

int64_t jy_push_counters_encode(
    const uint8_t* name, int64_t name_len, int64_t n_keys,
    const uint8_t* key_base, const int64_t* key_off, const int64_t* key_len,
    int32_t ndicts, const int64_t* dict_counts,  // n_keys * ndicts
    const uint64_t* rids, const uint64_t* vals,  // flattened entries
    uint8_t* out, int64_t out_cap) {
  Writer w{out, out + out_cap};
  w.u8(3);
  w.bytes(name, name_len);
  w.varint(static_cast<uint64_t>(n_keys));
  int64_t e = 0;
  // the wire orders each dict's entries by replica id; spans arrive in
  // Python dict-iteration order and sort HERE (insertion sort on the
  // small spans — the per-key sorted() this replaces dominated encode)
  uint64_t sr[64];
  uint64_t sv[64];
  for (int64_t k = 0; k < n_keys; k++) {
    w.bytes(key_base + key_off[k], key_len[k]);
    for (int32_t d = 0; d < ndicts; d++) {
      int64_t c = dict_counts[k * ndicts + d];
      w.varint(static_cast<uint64_t>(c));
      if (c <= 64) {
        for (int64_t i = 0; i < c; i++) {
          uint64_t r = rids[e + i], v = vals[e + i];
          int64_t j = i;
          while (j > 0 && sr[j - 1] > r) {
            sr[j] = sr[j - 1];
            sv[j] = sv[j - 1];
            j--;
          }
          sr[j] = r;
          sv[j] = v;
        }
        for (int64_t i = 0; i < c; i++) {
          w.varint(sr[i]);
          w.varint(sv[i]);
        }
        e += c;
      } else {
        std::vector<std::pair<uint64_t, uint64_t>> big;
        big.reserve(c);
        for (int64_t i = 0; i < c; i++, e++) big.emplace_back(rids[e], vals[e]);
        std::sort(big.begin(), big.end());
        for (auto& rv : big) {
          w.varint(rv.first);
          w.varint(rv.second);
        }
      }
    }
  }
  return w.ok ? (w.p - out) : -1;
}

// body starts AT the batch-count varint (caller has read tag + name).
int32_t jy_push_counters_measure(const uint8_t* body, int64_t body_len,
                                 int32_t ndicts, int64_t* n_keys_out,
                                 int64_t* total_entries_out) {
  Reader r{body, body, body + body_len};
  int64_t n_keys = r.count();
  int64_t total = 0;
  for (int64_t k = 0; k < n_keys && !r.rc; k++) {
    int64_t klen;
    r.bytes(&klen);
    for (int32_t d = 0; d < ndicts && !r.rc; d++) {
      int64_t c = r.count();
      total += c;
      for (int64_t i = 0; i < c && !r.rc; i++) {
        r.varint();
        r.varint();
      }
    }
  }
  if (r.rc) return r.rc;
  if (!r.done()) return -1;  // trailing bytes after message
  *n_keys_out = n_keys;
  *total_entries_out = total;
  return 0;
}

int32_t jy_push_counters_decode(const uint8_t* body, int64_t body_len,
                                int32_t ndicts, int64_t* key_off,
                                int64_t* key_len, int64_t* dict_counts,
                                uint64_t* rids, uint64_t* vals) {
  Reader r{body, body, body + body_len};
  int64_t n_keys = r.count();
  int64_t e = 0;
  for (int64_t k = 0; k < n_keys && !r.rc; k++) {
    key_off[k] = r.bytes(&key_len[k]);
    for (int32_t d = 0; d < ndicts && !r.rc; d++) {
      int64_t c = r.count();
      dict_counts[k * ndicts + d] = c;
      for (int64_t i = 0; i < c && !r.rc; i++, e++) {
        rids[e] = r.varint();
        vals[e] = r.varint();
      }
    }
  }
  return r.rc;
}

// ---- TREG: per key (value:bytes ts:varint) ---------------------------------

int64_t jy_push_treg_encode(const uint8_t* name, int64_t name_len,
                            int64_t n_keys, const uint8_t* key_base,
                            const int64_t* key_off, const int64_t* key_len,
                            const uint8_t* val_base, const int64_t* val_off,
                            const int64_t* val_len, const uint64_t* ts,
                            uint8_t* out, int64_t out_cap) {
  Writer w{out, out + out_cap};
  w.u8(3);
  w.bytes(name, name_len);
  w.varint(static_cast<uint64_t>(n_keys));
  for (int64_t k = 0; k < n_keys; k++) {
    w.bytes(key_base + key_off[k], key_len[k]);
    w.bytes(val_base + val_off[k], val_len[k]);
    w.varint(ts[k]);
  }
  return w.ok ? (w.p - out) : -1;
}

int32_t jy_push_treg_measure(const uint8_t* body, int64_t body_len,
                             int64_t* n_keys_out) {
  Reader r{body, body, body + body_len};
  int64_t n_keys = r.count();
  for (int64_t k = 0; k < n_keys && !r.rc; k++) {
    int64_t len;
    r.bytes(&len);
    r.bytes(&len);
    r.varint();
  }
  if (r.rc) return r.rc;
  if (!r.done()) return -1;
  *n_keys_out = n_keys;
  return 0;
}

int32_t jy_push_treg_decode(const uint8_t* body, int64_t body_len,
                            int64_t* key_off, int64_t* key_len,
                            int64_t* val_off, int64_t* val_len, uint64_t* ts) {
  Reader r{body, body, body + body_len};
  int64_t n_keys = r.count();
  for (int64_t k = 0; k < n_keys && !r.rc; k++) {
    key_off[k] = r.bytes(&key_len[k]);
    val_off[k] = r.bytes(&val_len[k]);
    ts[k] = r.varint();
  }
  return r.rc;
}

// ---- TLOG / SYSTEM: per key (entries:[(value:bytes ts:varint)] cutoff) -----

int64_t jy_push_tlog_encode(const uint8_t* name, int64_t name_len,
                            int64_t n_keys, const uint8_t* key_base,
                            const int64_t* key_off, const int64_t* key_len,
                            const int64_t* entry_counts,
                            const uint8_t* ent_base, const int64_t* ent_off,
                            const int64_t* ent_len, const uint64_t* ent_ts,
                            const uint64_t* cutoffs, uint8_t* out,
                            int64_t out_cap) {
  Writer w{out, out + out_cap};
  w.u8(3);
  w.bytes(name, name_len);
  w.varint(static_cast<uint64_t>(n_keys));
  int64_t e = 0;
  for (int64_t k = 0; k < n_keys; k++) {
    w.bytes(key_base + key_off[k], key_len[k]);
    int64_t c = entry_counts[k];
    w.varint(static_cast<uint64_t>(c));
    for (int64_t i = 0; i < c; i++, e++) {
      w.bytes(ent_base + ent_off[e], ent_len[e]);
      w.varint(ent_ts[e]);
    }
    w.varint(cutoffs[k]);
  }
  return w.ok ? (w.p - out) : -1;
}

int32_t jy_push_tlog_measure(const uint8_t* body, int64_t body_len,
                             int64_t* n_keys_out, int64_t* total_entries_out) {
  Reader r{body, body, body + body_len};
  int64_t n_keys = r.count();
  int64_t total = 0;
  for (int64_t k = 0; k < n_keys && !r.rc; k++) {
    int64_t len;
    r.bytes(&len);
    int64_t c = r.count();
    total += c;
    for (int64_t i = 0; i < c && !r.rc; i++) {
      r.bytes(&len);
      r.varint();
    }
    r.varint();
  }
  if (r.rc) return r.rc;
  if (!r.done()) return -1;
  *n_keys_out = n_keys;
  *total_entries_out = total;
  return 0;
}

int32_t jy_push_tlog_decode(const uint8_t* body, int64_t body_len,
                            int64_t* key_off, int64_t* key_len,
                            int64_t* entry_counts, int64_t* ent_off,
                            int64_t* ent_len, uint64_t* ent_ts,
                            uint64_t* cutoffs) {
  Reader r{body, body, body + body_len};
  int64_t n_keys = r.count();
  int64_t e = 0;
  for (int64_t k = 0; k < n_keys && !r.rc; k++) {
    key_off[k] = r.bytes(&key_len[k]);
    int64_t c = r.count();
    entry_counts[k] = c;
    for (int64_t i = 0; i < c && !r.rc; i++, e++) {
      ent_off[e] = r.bytes(&ent_len[e]);
      ent_ts[e] = r.varint();
    }
    cutoffs[k] = r.varint();
  }
  return r.rc;
}

// ---- UJSON: per key (entries:[(rid seq path:[str] token:str)]
//                      vv:[(rid seq)] cloud:[(rid seq)]) ---------------------
// The Python wrapper flattens each delta in oracle order (entries sorted by
// dot, vv by rid, cloud sorted); strings are path parts then token per
// entry, all in one blob. counts holds 3 int64 per key: entries, vv, cloud.

int64_t jy_push_ujson_encode(
    const uint8_t* name, int64_t name_len, int64_t n_keys,
    const uint8_t* key_base, const int64_t* key_off, const int64_t* key_len,
    const int64_t* counts, const uint64_t* ent_rid, const uint64_t* ent_seq,
    const int64_t* path_counts, const uint8_t* str_base,
    const int64_t* str_off, const int64_t* str_len, const uint64_t* vv_rid,
    const uint64_t* vv_val, const uint64_t* cl_rid, const uint64_t* cl_seq,
    uint8_t* out, int64_t out_cap) {
  Writer w{out, out + out_cap};
  w.u8(3);
  w.bytes(name, name_len);
  w.varint(static_cast<uint64_t>(n_keys));
  int64_t e = 0, s = 0, v = 0, c = 0;
  for (int64_t k = 0; k < n_keys; k++) {
    w.bytes(key_base + key_off[k], key_len[k]);
    int64_t ne = counts[k * 3], nv = counts[k * 3 + 1], nc = counts[k * 3 + 2];
    w.varint(static_cast<uint64_t>(ne));
    for (int64_t i = 0; i < ne; i++, e++) {
      w.varint(ent_rid[e]);
      w.varint(ent_seq[e]);
      int64_t np = path_counts[e];
      w.varint(static_cast<uint64_t>(np));
      for (int64_t j = 0; j <= np; j++, s++) {  // path parts, then token
        w.bytes(str_base + str_off[s], str_len[s]);
      }
    }
    w.varint(static_cast<uint64_t>(nv));
    for (int64_t i = 0; i < nv; i++, v++) {
      w.varint(vv_rid[v]);
      w.varint(vv_val[v]);
    }
    w.varint(static_cast<uint64_t>(nc));
    for (int64_t i = 0; i < nc; i++, c++) {
      w.varint(cl_rid[c]);
      w.varint(cl_seq[c]);
    }
  }
  return w.ok ? (w.p - out) : -1;
}

// (UJSON decode lives in native/ujson_planes.cpp: the receive path
// splits the body into lazy per-key payload spans instead of walking
// every entry into flat arrays here.)

}  // extern "C"
