// Shared host-state tables for the native serving engine.
//
// The reference executes every command inside compiled Pony actors
// scheduled across all cores (jylis/server_notify.pony:8-36,
// jylis/repo_manager.pony:18); the rebuild's Python serving seam tops out
// on interpreter dispatch. These tables own the per-type HOST state the
// Python repos otherwise keep in dicts, so whole pipelined bursts of ANY
// data type settle in one FFI call (native/serve_engine.cpp): parse (via
// resp_scan, same .so) + table update + reply bytes, all in C++.
//
// Split of responsibilities (single source of truth):
//   * native: key tables, serving winners/caches, pending windows, delta
//     accumulators — everything a command touches on the hot path
//   * Python: device drains, cluster converge orchestration, snapshots —
//     via the bulk export/apply calls in the .cpp files
// Any command the engine can't settle exactly like the Python oracle is
// returned to Python with its argument slices; the caller applies THAT
// command (after draining the UJSON write queue, which preserves
// per-connection ordering) and re-enters.

#pragma once

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

extern "C" int32_t resp_scan(const uint8_t* buf, int64_t len,
                             int64_t* consumed, int64_t* offs, int64_t* lens,
                             int32_t max_args, int32_t* n_args);

namespace jy {

// ---- open-addressing key index (FNV-1a, power-of-two, linear probe) --------

struct KeyIndex {
    std::vector<int64_t> slot_row;  // -1 empty
    std::vector<uint8_t> arena;     // key bytes, append-only
    std::vector<int64_t> key_off;
    std::vector<int64_t> key_len;
    std::vector<uint64_t> key_hash;

    KeyIndex() : slot_row(64, -1) {}

    size_t mask() const { return slot_row.size() - 1; }
    int64_t rows() const { return static_cast<int64_t>(key_off.size()); }

    static uint64_t hash(const uint8_t* k, int64_t n) {
        uint64_t h = 1469598103934665603ull;
        for (int64_t i = 0; i < n; i++) h = (h ^ k[i]) * 1099511628211ull;
        return h;
    }

    bool key_eq(int64_t row, const uint8_t* k, int64_t n) const {
        return key_len[row] == n &&
               memcmp(arena.data() + key_off[row], k,
                      static_cast<size_t>(n)) == 0;
    }

    void rehash() {
        std::vector<int64_t> fresh(slot_row.size() * 2, -1);
        size_t m = fresh.size() - 1;
        for (size_t r = 0; r < key_off.size(); r++) {
            size_t i = key_hash[r] & m;
            while (fresh[i] >= 0) i = (i + 1) & m;
            fresh[i] = static_cast<int64_t>(r);
        }
        slot_row.swap(fresh);
    }

    int64_t find(const uint8_t* k, int64_t n) const {
        uint64_t h = hash(k, n);
        size_t i = h & mask();
        while (true) {
            int64_t row = slot_row[i];
            if (row < 0) return -1;
            if (key_hash[row] == h && key_eq(row, k, n)) return row;
            i = (i + 1) & mask();
        }
    }

    // returns (row, was_new): callers append their per-row columns on new
    std::pair<int64_t, bool> upsert(const uint8_t* k, int64_t n) {
        uint64_t h = hash(k, n);
        size_t i = h & mask();
        while (true) {
            int64_t row = slot_row[i];
            if (row < 0) break;
            if (key_hash[row] == h && key_eq(row, k, n)) return {row, false};
            i = (i + 1) & mask();
        }
        int64_t row = rows();
        key_off.push_back(static_cast<int64_t>(arena.size()));
        key_len.push_back(n);
        key_hash.push_back(h);
        arena.insert(arena.end(), k, k + n);
        slot_row[i] = row;
        if (key_off.size() * 10 >= slot_row.size() * 7) rehash();
        return {row, true};
    }

    const uint8_t* key_ptr(int64_t row) const {
        return arena.data() + key_off[row];
    }
};

// ---- counter table (GCOUNT / PNCOUNT) --------------------------------------

constexpr uint8_t F_FOREIGN = 1;
constexpr uint8_t F_DIRTY = 2;
constexpr uint8_t F_PEND_P = 4;
constexpr uint8_t F_PEND_N = 8;
// "own was ever written" per polarity: flush emits a polarity's entry
// only when set, matching the Python dicts' key-presence semantics
// (an INC of 0 still creates the entry)
constexpr uint8_t F_OWNSET_P = 16;
constexpr uint8_t F_OWNSET_N = 32;
// row changed since the last sync-digest pass (cluster/syncdigest)
constexpr uint8_t F_SYNCD = 64;

struct Table {
    KeyIndex idx;
    // per-row state
    std::vector<uint64_t> value;  // serving value (u64 bits)
    std::vector<uint64_t> own_p;
    std::vector<uint64_t> own_n;
    std::vector<uint64_t> pend_p;  // max own within the drain window
    std::vector<uint64_t> pend_n;
    std::vector<uint8_t> flags;
    std::vector<int64_t> dirty_rows;  // insertion order; F_DIRTY dedups
    std::vector<int64_t> pend_rows;   // rows with any F_PEND_*
    std::vector<int64_t> sync_dirty;  // rows changed since last digest

    int64_t find(const uint8_t* k, int64_t n) const { return idx.find(k, n); }

    int64_t upsert(const uint8_t* k, int64_t n) {
        auto [row, fresh] = idx.upsert(k, n);
        if (fresh) {
            value.push_back(0);
            own_p.push_back(0);
            own_n.push_back(0);
            pend_p.push_back(0);
            pend_n.push_back(0);
            flags.push_back(0);
        }
        return row;
    }

    void mark_dirty(int64_t row) {
        if (!(flags[row] & F_DIRTY)) {
            flags[row] |= F_DIRTY;
            dirty_rows.push_back(row);
        }
    }

    // INC (polarity 0) / DEC (polarity 1): the exact sequence of
    // repo_counters.py _inc / PN apply
    void bump(int64_t row, int polarity, uint64_t amount) {
        uint64_t& own = polarity ? own_n[row] : own_p[row];
        uint64_t& pend = polarity ? pend_n[row] : pend_p[row];
        uint8_t bit = polarity ? F_PEND_N : F_PEND_P;
        flags[row] |= polarity ? F_OWNSET_N : F_OWNSET_P;
        own += amount;  // u64 wrap
        if (own > pend) pend = own;
        if (!(flags[row] & (F_PEND_P | F_PEND_N))) pend_rows.push_back(row);
        flags[row] |= bit;
        mark_dirty(row);
        if (!(flags[row] & F_SYNCD)) {
            flags[row] |= F_SYNCD;
            sync_dirty.push_back(row);
        }
        value[row] += polarity ? static_cast<uint64_t>(-amount) : amount;
    }
};

// ---- TREG table ------------------------------------------------------------
//
// Last-writer-wins registers (jylis/repo_treg.pony:11-68). The winner rule
// is lexicographic (ts, value-bytes) — exactly models/repo_treg.py's host
// compare, so the native winner NEVER needs a device read-back: a drain
// just folds the pending window into the drained cache (the join of what
// both already hold), and the device converges to the same winner.

struct TregTable {
    KeyIndex idx;
    // drained winner (the device mirror's exact host image)
    std::vector<uint64_t> cache_ts;
    std::vector<std::string> cache_val;
    std::vector<uint8_t> cache_set;
    // max (ts, value) written since the last drain
    std::vector<uint64_t> pend_ts;
    std::vector<std::string> pend_val;
    std::vector<uint8_t> pend_set;
    std::vector<int64_t> pend_rows;  // rows with pend_set, insertion order
    // max (ts, value) written locally since the last flush
    std::vector<uint64_t> delta_ts;
    std::vector<std::string> delta_val;
    std::vector<uint8_t> delta_set;
    std::vector<int64_t> delta_rows;
    // rows changed since the last sync-digest pass
    std::vector<uint8_t> sync_flag;
    std::vector<int64_t> sync_dirty;

    static bool wins(uint64_t ts, const uint8_t* v, int64_t n,
                     uint64_t cur_ts, const std::string& cur) {
        if (ts != cur_ts) return ts > cur_ts;
        size_t cn = cur.size();
        size_t m = static_cast<size_t>(n) < cn ? n : cn;
        int c = memcmp(v, cur.data(), m);
        if (c != 0) return c > 0;
        return static_cast<size_t>(n) > cn;
    }

    int64_t upsert(const uint8_t* k, int64_t n) {
        auto [row, fresh] = idx.upsert(k, n);
        if (fresh) {
            cache_ts.push_back(0);
            cache_val.emplace_back();
            cache_set.push_back(0);
            pend_ts.push_back(0);
            pend_val.emplace_back();
            pend_set.push_back(0);
            delta_ts.push_back(0);
            delta_val.emplace_back();
            delta_set.push_back(0);
            sync_flag.push_back(0);
        }
        return row;
    }

    // local SET / cluster converge both funnel here (repo_treg.py _write)
    void write(int64_t row, uint64_t ts, const uint8_t* v, int64_t n) {
        if (!sync_flag[row]) {
            sync_flag[row] = 1;
            sync_dirty.push_back(row);
        }
        if (!pend_set[row]) {
            pend_set[row] = 1;
            pend_ts[row] = ts;
            pend_val[row].assign(reinterpret_cast<const char*>(v), n);
            pend_rows.push_back(row);
        } else if (wins(ts, v, n, pend_ts[row], pend_val[row])) {
            pend_ts[row] = ts;
            pend_val[row].assign(reinterpret_cast<const char*>(v), n);
        }
    }

    void note_delta(int64_t row, uint64_t ts, const uint8_t* v, int64_t n) {
        if (!delta_set[row]) {
            delta_set[row] = 1;
            delta_ts[row] = ts;
            delta_val[row].assign(reinterpret_cast<const char*>(v), n);
            delta_rows.push_back(row);
        } else if (wins(ts, v, n, delta_ts[row], delta_val[row])) {
            delta_ts[row] = ts;
            delta_val[row].assign(reinterpret_cast<const char*>(v), n);
        }
    }

    // serving winner = join(cache, pend); returns false when the row has
    // never been written (GET -> null)
    bool winner(int64_t row, uint64_t* ts, const std::string** val) const {
        if (!cache_set[row] && !pend_set[row]) return false;
        if (!pend_set[row] ||
            (cache_set[row] &&
             !wins(pend_ts[row],
                   reinterpret_cast<const uint8_t*>(pend_val[row].data()),
                   static_cast<int64_t>(pend_val[row].size()), cache_ts[row],
                   cache_val[row]))) {
            *ts = cache_ts[row];
            *val = &cache_val[row];
        } else {
            *ts = pend_ts[row];
            *val = &pend_val[row];
        }
        return true;
    }

    // drain epilogue: the pending window folds into the drained cache
    // (the join both sides already agree on) and clears
    void fold_pending() {
        for (int64_t row : pend_rows) {
            if (!cache_set[row] ||
                wins(pend_ts[row],
                     reinterpret_cast<const uint8_t*>(pend_val[row].data()),
                     static_cast<int64_t>(pend_val[row].size()), cache_ts[row],
                     cache_val[row])) {
                cache_ts[row] = pend_ts[row];
                cache_val[row] = pend_val[row];
                cache_set[row] = 1;
            }
            pend_set[row] = 0;
            pend_val[row].clear();
        }
        pend_rows.clear();
    }
};

// ---- TLOG table ------------------------------------------------------------
//
// Timestamped logs with grow-only cutoff (jylis/repo_tlog.pony:16-111,
// docs tlog.md). Entries intern their value bytes once; the per-row
// merged view (drained ∪ pending, deduped on (ts, value), cutoff-
// filtered) is the SIZE serving surface — the exact mirror of
// models/repo_tlog.py's _merged_set memo, including its validity states.
// The drained "base" carries ACROSS drains: when the memo is current at
// drain time, the post-drain row content is exactly the memo filtered by
// the new cutoff, so SIZE keeps serving natively without ever reading
// the device back.

struct TlogEnt {
    uint64_t ts;
    int32_t vid;
    bool operator==(const TlogEnt& o) const {
        return ts == o.ts && vid == o.vid;
    }
};

struct TlogEntHash {
    size_t operator()(const TlogEnt& e) const {
        uint64_t h = e.ts * 0x9E3779B97F4A7C15ull;
        h ^= static_cast<uint64_t>(static_cast<uint32_t>(e.vid)) + (h >> 29);
        return static_cast<size_t>(h * 0xBF58476D1CE4E5B9ull);
    }
};

using TlogSet = std::unordered_set<TlogEnt, TlogEntHash>;

struct TlogRow {
    std::vector<TlogEnt> pend;  // un-drained entries, arrival order
    uint64_t pend_cutoff = 0;   // max incoming/trim cutoff not yet drained
    bool touched = false;       // in TlogTable::touched_list
    int64_t len_cache = 0;      // drained length (post-cutoff)
    uint64_t cut_cache = 0;     // drained cutoff
    // drained entries as a set-buildable list; valid when it exactly
    // mirrors the device row (maintained across drains via the memo)
    std::vector<TlogEnt> base;
    bool base_valid = true;  // new rows have an empty drained part
    // the merged-view memo: current when (memo_plen, memo_cut) matches
    // (pend.size(), cutoff_view) — repo_tlog.py _merged_set's state key
    TlogSet memo;
    bool memo_valid = false;
    int64_t memo_plen = 0;
    uint64_t memo_cut = 0;
    uint64_t gen = 0;  // bumped whenever the merged view may have changed
    // GET-order memo: the merged view sorted (ts, value-bytes) desc —
    // the native mirror of repo_tlog.py's _sorted cache, keyed by gen
    std::vector<TlogEnt> sorted_view;
    uint64_t sorted_gen = 0;
    bool sorted_valid = false;
    // delta accumulator (hostref.TLog): entry set + grow-only cutoff
    bool delta_present = false;
    TlogSet delta;
    uint64_t delta_cutoff = 0;
    bool sync_flag = false;  // in TlogTable::sync_dirty
};

struct TlogTable {
    KeyIndex idx;
    std::vector<TlogRow> rows;
    // value interner: vid -> bytes, bytes -> vid
    std::vector<std::string> vals;
    std::unordered_map<std::string, int32_t> vmap;
    int64_t pend_rows_count = 0;  // rows with non-empty pend
    bool row_overdue = false;     // some row's pend crossed ROW_DRAIN
    std::vector<int64_t> delta_rows;    // rows with delta_present
    std::vector<int64_t> touched_list;  // rows with pend or pend_cutoff
    std::vector<int64_t> sync_dirty;    // rows changed since last digest
    int64_t live_total = 0;  // sum of len_cache over all rows (O(1) reads)
    int64_t compact_floor;  // value-interner size below which no compact

    static constexpr int64_t ROW_DRAIN_THRESHOLD = 1024;   // repo_tlog.py:40
    static constexpr int64_t PENDING_DRAIN_THRESHOLD = 4096;
    static constexpr int64_t VAL_COMPACT_SLACK = 8192;

    TlogTable() : compact_floor(VAL_COMPACT_SLACK) {}

    int32_t intern(const uint8_t* v, int64_t n) {
        std::string s(reinterpret_cast<const char*>(v), n);
        auto it = vmap.find(s);
        if (it != vmap.end()) return it->second;
        int32_t id = static_cast<int32_t>(vals.size());
        vals.push_back(std::move(s));
        vmap.emplace(vals.back(), id);
        return id;
    }

    int64_t upsert(const uint8_t* k, int64_t n) {
        auto [row, fresh] = idx.upsert(k, n);
        if (fresh) rows.emplace_back();
        return row;
    }

    uint64_t cutoff_view(const TlogRow& r) const {
        return r.pend_cutoff > r.cut_cache ? r.pend_cutoff : r.cut_cache;
    }

    bool quiescent(const TlogRow& r) const {
        return r.pend.empty() && r.pend_cutoff <= r.cut_cache;
    }

    bool memo_current(const TlogRow& r) const {
        return r.memo_valid &&
               r.memo_plen == static_cast<int64_t>(r.pend.size()) &&
               r.memo_cut == cutoff_view(r);
    }

    void touch(TlogRow& r, int64_t row_i) {
        if (!r.touched) {
            r.touched = true;
            touched_list.push_back(row_i);
        }
        mark_sync(r, row_i);
    }

    void mark_sync(TlogRow& r, int64_t row_i) {
        if (!r.sync_flag) {
            r.sync_flag = true;
            sync_dirty.push_back(row_i);
        }
    }

    void append_pend(TlogRow& r, int64_t row_i, TlogEnt e) {
        if (r.pend.empty()) pend_rows_count++;
        r.pend.push_back(e);
        touch(r, row_i);
        if (static_cast<int64_t>(r.pend.size()) >= ROW_DRAIN_THRESHOLD)
            row_overdue = true;
    }

    // local INS (repo_tlog.py apply INS): pend append + memo upkeep
    // (_note_local_insert) + delta insert when ts clears the drained
    // cutoff
    void ins(int64_t row_i, uint64_t ts, const uint8_t* v, int64_t n) {
        TlogRow& r = rows[row_i];
        TlogEnt e{ts, intern(v, n)};
        append_pend(r, row_i, e);
        r.gen++;
        if (r.memo_valid) {
            uint64_t cut = cutoff_view(r);
            if (r.memo_plen != static_cast<int64_t>(r.pend.size()) - 1 ||
                r.memo_cut != cut) {
                r.memo_valid = false;
                TlogSet().swap(r.memo);  // free, don't retain dead sets
            } else {
                if (ts >= cut) r.memo.insert(e);
                r.memo_plen = static_cast<int64_t>(r.pend.size());
                r.memo_cut = cut;
            }
        }
        if (ts >= r.cut_cache) {
            if (!r.delta_present) {
                r.delta_present = true;
                delta_rows.push_back(row_i);
            }
            if (ts >= r.delta_cutoff) r.delta.insert(e);
        }
    }

    // cluster converge: entries/cutoff buffer without memo upkeep (the
    // memo's state key goes stale, exactly like the Python dict path)
    void converge_entry(int64_t row_i, uint64_t ts, const uint8_t* v,
                        int64_t n) {
        TlogRow& r = rows[row_i];
        append_pend(r, row_i, TlogEnt{ts, intern(v, n)});
        r.gen++;
    }

    void raise_pend_cutoff(int64_t row_i, uint64_t c) {
        TlogRow& r = rows[row_i];
        if (c > r.pend_cutoff) {
            r.pend_cutoff = c;
            touch(r, row_i);
            r.gen++;
        }
    }

    // merged-view size; -1 when the drained base is unknown (Python
    // rebuilds it from a device gather and calls set_base)
    int64_t size(int64_t row_i) {
        TlogRow& r = rows[row_i];
        if (quiescent(r)) return r.len_cache;
        if (memo_current(r)) return static_cast<int64_t>(r.memo.size());
        if (!r.base_valid) return -1;
        uint64_t cut = cutoff_view(r);
        r.memo.clear();
        for (const TlogEnt& e : r.base)
            if (e.ts >= cut) r.memo.insert(e);
        for (const TlogEnt& e : r.pend)
            if (e.ts >= cut) r.memo.insert(e);
        r.memo_valid = true;
        r.memo_plen = static_cast<int64_t>(r.pend.size());
        r.memo_cut = cut;
        r.gen++;
        return static_cast<int64_t>(r.memo.size());
    }

    // the merged view sorted (ts, value-bytes) desc — TLOG GET's serving
    // order (repo_tlog.py _merged_view). Returns nullptr when the drained
    // base is unknown (Python rebuilds it from a device gather) — the
    // caller defers the command. Cached per row, keyed by gen.
    const std::vector<TlogEnt>* sorted_view_of(int64_t row_i) {
        TlogRow& r = rows[row_i];
        if (size(row_i) < 0) return nullptr;  // base unknown: defer
        if (r.sorted_valid && r.sorted_gen == r.gen) return &r.sorted_view;
        r.sorted_view.clear();
        if (quiescent(r)) {
            if (!r.base_valid) return nullptr;  // device row render needed
            r.sorted_view = r.base;
        } else if (memo_current(r)) {
            r.sorted_view.assign(r.memo.begin(), r.memo.end());
        } else {
            return nullptr;  // unreachable after size() >= 0; stay safe
        }
        std::sort(r.sorted_view.begin(), r.sorted_view.end(),
                  [this](const TlogEnt& a, const TlogEnt& b) {
                      if (a.ts != b.ts) return a.ts > b.ts;
                      return vals[b.vid] < vals[a.vid];  // value desc
                  });
        r.sorted_valid = true;
        r.sorted_gen = r.gen;
        return &r.sorted_view;
    }

    static void drop_sorted(TlogRow& r) {
        r.sorted_valid = false;
        std::vector<TlogEnt>().swap(r.sorted_view);
    }

    // drain epilogue for one drained row: device reported (len, cut)
    void finish_drain_row(int64_t row_i, int64_t len, uint64_t cut) {
        TlogRow& r = rows[row_i];
        drop_sorted(r);  // free rather than wait for the gen-key miss
        bool memo_cur = memo_current(r);
        if (memo_cur) {
            r.base.clear();
            for (const TlogEnt& e : r.memo)
                if (e.ts >= cut) r.base.push_back(e);
            r.base_valid = static_cast<int64_t>(r.base.size()) == len;
        } else {
            r.base.clear();
            r.base_valid = (len == 0);
        }
        mark_sync(r, row_i);  // a fused trim can change the merged view
        live_total += len - r.len_cache;
        r.len_cache = len;
        r.cut_cache = cut;
        if (!r.pend.empty()) pend_rows_count--;
        r.pend.clear();
        r.pend_cutoff = 0;
        if (r.base_valid) {
            r.memo.clear();
            r.memo.insert(r.base.begin(), r.base.end());
            r.memo_valid = true;
            r.memo_plen = 0;
            r.memo_cut = cutoff_view(r);
        } else {
            r.memo_valid = false;
            r.memo.clear();
        }
        r.gen++;
    }

    // global drain tail: mirrors repo_tlog.py _finish_drain's
    // pend.clear() across every row + flag reset
    void finish_drain_end() {
        for (int64_t row_i : touched_list) {
            TlogRow& r = rows[row_i];
            r.touched = false;
            if (!r.pend.empty()) {  // touched but not in the drain set:
                r.pend.clear();     // cannot happen under the repo lock,
                r.memo_valid = false;  // but mirror the global clear
                r.gen++;
            }
            r.pend_cutoff = 0;
        }
        touched_list.clear();
        pend_rows_count = 0;
        row_overdue = false;
    }

    // value-interner epoch compaction (the host analog of the repo's
    // device-vid _maybe_compact_interner): once the table holds far more
    // strings than the live entry set references, remap every live vid
    // and drop the garbage. Returns true when a remap happened — callers
    // holding vid->bytes mirrors must reset them.
    bool compact_values() {
        if (static_cast<int64_t>(vals.size()) < compact_floor) return 0;
        std::vector<char> mark(vals.size(), 0);
        int64_t live = 0;
        auto see = [&](const TlogEnt& e) {
            if (e.vid >= 0 && !mark[e.vid]) {
                mark[e.vid] = 1;
                live++;
            }
        };
        for (TlogRow& r : rows) {
            for (const TlogEnt& e : r.pend) see(e);
            for (const TlogEnt& e : r.base) see(e);
            if (memo_current(r)) {
                for (const TlogEnt& e : r.memo) see(e);
            } else if (!r.memo.empty()) {
                // a state-stale memo (e.g. converge_entry appended past
                // it) is dead weight: free it rather than keeping its
                // vids alive through the compaction
                r.memo_valid = false;
                TlogSet().swap(r.memo);
            }
            for (const TlogEnt& e : r.delta) see(e);
        }
        if (static_cast<int64_t>(vals.size()) <= 2 * live + VAL_COMPACT_SLACK) {
            // genuinely live: raise the floor so the walk stays amortised
            compact_floor = static_cast<int64_t>(vals.size()) + VAL_COMPACT_SLACK;
            return 0;
        }
        std::vector<int32_t> remap(vals.size(), -1);
        std::vector<std::string> fresh;
        fresh.reserve(live);
        for (size_t i = 0; i < vals.size(); i++) {
            if (mark[i]) {
                remap[i] = static_cast<int32_t>(fresh.size());
                fresh.push_back(std::move(vals[i]));
            }
        }
        vals.swap(fresh);
        vmap.clear();
        for (size_t i = 0; i < vals.size(); i++)
            vmap.emplace(vals[i], static_cast<int32_t>(i));
        auto fix_vec = [&](std::vector<TlogEnt>& v) {
            for (TlogEnt& e : v)
                if (e.vid >= 0) e.vid = remap[e.vid];
        };
        auto fix_set = [&](TlogSet& s) {
            TlogSet out;
            out.reserve(s.size());
            for (TlogEnt e : s) {
                if (e.vid >= 0) e.vid = remap[e.vid];
                out.insert(e);
            }
            s.swap(out);
        };
        for (TlogRow& r : rows) {
            fix_vec(r.pend);
            fix_vec(r.base);
            fix_set(r.memo);
            fix_set(r.delta);
            // the GET-order cache holds vids too; a stale (old-gen) copy
            // may reference dead ids the remap never saw — drop it
            drop_sorted(r);
        }
        compact_floor =
            2 * static_cast<int64_t>(vals.size()) + VAL_COMPACT_SLACK;
        return 1;
    }
};

// ---- UJSON serving memo ----------------------------------------------------
//
// The ORSWOT document lattice stays in Python (host docs) or on the
// device (resident rows) — the engine never owns it. What it owns is the
// RENDER memo: per key, the exact reply bytes the Python oracle produced
// for `UJSON GET key [path...]`, keyed by the path argument vector. The
// Python GET path installs an entry after serving (repo_ujson.py), and
// every write invalidates the overlapping entries — natively at
// queue-bank time, from Python on converge/apply. This mirrors the TLOG
// merged-view memo contract: the memo is only ever a cache of what the
// oracle already rendered, a miss defers to Python (which repairs the
// memo while serving), and staleness is impossible because invalidation
// happens under the same repo-lock boundary as the write itself.
//
// Path keys are length-prefixed blobs (u32 len + bytes per component),
// which makes component-prefix exactly byte-prefix — so the precise
// invalidation rules are cheap:
//   * INS/RM at path p change only renders at paths q ⊆ p (q a prefix
//     of p): deeper disjoint subtrees keep serving natively;
//   * SET/CLR at p rewrite the subtree: q ⊆ p or p ⊆ q invalidates.

struct UjsonTable {
    KeyIndex idx;
    // row -> path-blob -> full reply payload ($len\r\nrender\r\n)
    std::vector<std::unordered_map<std::string, std::string>> memo;

    // renders cached per key; above this the row's map resets (GET paths
    // per key are few in practice — the cap only bounds pathology)
    static constexpr size_t MEMO_PER_KEY = 8;

    int64_t upsert(const uint8_t* k, int64_t n) {
        auto [row, fresh] = idx.upsert(k, n);
        if (fresh) memo.emplace_back();
        return row;
    }

    void put(int64_t row, std::string path, std::string reply) {
        auto& m = memo[row];
        if (m.size() >= MEMO_PER_KEY && m.find(path) == m.end()) m.clear();
        m[std::move(path)] = std::move(reply);
    }

    const std::string* get(int64_t row, const std::string& path) const {
        const auto& m = memo[row];
        auto it = m.find(path);
        return it == m.end() ? nullptr : &it->second;
    }

    static bool is_prefix(const std::string& a, const std::string& b) {
        return a.size() <= b.size() &&
               memcmp(a.data(), b.data(), a.size()) == 0;
    }

    // invalidate the renders a write at `path` can change; subtree=true
    // for SET/CLR (both prefix directions), false for INS/RM
    void invalidate(int64_t row, const std::string& path, bool subtree) {
        auto& m = memo[row];
        for (auto it = m.begin(); it != m.end();) {
            bool hit = is_prefix(it->first, path) ||
                       (subtree && is_prefix(path, it->first));
            it = hit ? m.erase(it) : std::next(it);
        }
    }
};

// ---- UJSON value validators ------------------------------------------------
//
// A natively banked write replies +OK immediately, so the one thing the
// engine must prove is that the oracle's later apply CANNOT raise — i.e.
// the value arg parses as Python's json.loads would parse it
// (ops/ujson_host.py parse_value/parse_doc; the token actually stored is
// the oracle's own canonical dumps, so no round-trip identity is needed
// for equivalence). These validators accept exactly Python's strict JSON
// grammar: escape-bearing and \uXXXX strings, raw UTF-8 (the oracle
// decodes argument bytes with errors="replace", so any byte >= 0x20 is
// parseable), full int/frac/exp numbers, and the NaN/Infinity literals
// json.loads allows by default. Raw control bytes inside strings, bad
// escapes, leading zeros, lone '-', and trailing garbage all bounce.

inline bool json_ws(uint8_t c) {
    return c == ' ' || c == '\t' || c == '\n' || c == '\r';
}

// returns index past the closing quote, or -1
inline int64_t scan_json_string(const uint8_t* p, int64_t n, int64_t i) {
    i++;  // opening quote
    while (i < n) {
        uint8_t c = p[i];
        if (c == '"') return i + 1;
        if (c < 0x20) return -1;  // strict mode rejects raw controls
        if (c == '\\') {
            if (i + 1 >= n) return -1;
            uint8_t e = p[i + 1];
            if (e == 'u') {
                if (i + 5 >= n) return -1;
                for (int64_t j = i + 2; j < i + 6; j++) {
                    uint8_t h = p[j];
                    if (!((h >= '0' && h <= '9') || (h >= 'a' && h <= 'f') ||
                          (h >= 'A' && h <= 'F')))
                        return -1;
                }
                i += 6;
                continue;
            }
            if (e != '"' && e != '\\' && e != '/' && e != 'b' && e != 'f' &&
                e != 'n' && e != 'r' && e != 't')
                return -1;
            i += 2;
            continue;
        }
        i++;  // any other byte incl. raw UTF-8 (replace-decoded oracle-side)
    }
    return -1;
}

// Python json's number regex: -?(0|[1-9]\d*)(\.\d+)?([eE][+-]?\d+)?
// int() refuses digit strings past sys.int_max_str_digits (4300 by
// default), so an INTEGER token that long makes json.loads raise — stay
// comfortably below so a banked +OK can never turn into a late crash at
// queue-flush time (floats parse via float(), which has no such limit)
constexpr int64_t JSON_INT_DIGITS_MAX = 4000;

inline int64_t scan_json_number(const uint8_t* p, int64_t n, int64_t i) {
    if (i < n && p[i] == '-') i++;
    if (i >= n) return -1;
    int64_t int_start = i;
    if (p[i] == '0') {
        i++;
    } else if (p[i] >= '1' && p[i] <= '9') {
        while (i < n && p[i] >= '0' && p[i] <= '9') i++;
    } else {
        return -1;
    }
    if ((i >= n || (p[i] != '.' && p[i] != 'e' && p[i] != 'E')) &&
        i - int_start > JSON_INT_DIGITS_MAX)
        return -1;  // integer token: Python's int() conversion would raise
    if (i < n && p[i] == '.') {
        i++;
        if (i >= n || p[i] < '0' || p[i] > '9') return -1;
        while (i < n && p[i] >= '0' && p[i] <= '9') i++;
    }
    if (i < n && (p[i] == 'e' || p[i] == 'E')) {
        i++;
        if (i < n && (p[i] == '+' || p[i] == '-')) i++;
        if (i >= n || p[i] < '0' || p[i] > '9') return -1;
        while (i < n && p[i] >= '0' && p[i] <= '9') i++;
    }
    return i;
}

inline bool word_at(const uint8_t* p, int64_t n, int64_t i, const char* w) {
    int64_t wn = static_cast<int64_t>(strlen(w));
    return i + wn <= n && memcmp(p + i, w, static_cast<size_t>(wn)) == 0;
}

// literal constants json.loads accepts (allow_nan default); returns end
// index or -1
inline int64_t scan_json_literal(const uint8_t* p, int64_t n, int64_t i) {
    for (const char* w : {"true", "false", "null", "NaN", "Infinity",
                          "-Infinity"})
        if (word_at(p, n, i, w)) return i + static_cast<int64_t>(strlen(w));
    return -1;
}

// full JSON value (objects/arrays too), depth-capped so a pathologically
// nested doc defers to Python instead of recursing here; returns end or -1
inline int64_t scan_json_value(const uint8_t* p, int64_t n, int64_t i,
                               int depth) {
    if (depth <= 0) return -1;
    while (i < n && json_ws(p[i])) i++;
    if (i >= n) return -1;
    uint8_t c = p[i];
    if (c == '"') return scan_json_string(p, n, i);
    if (c == '{') {
        i++;
        while (i < n && json_ws(p[i])) i++;
        if (i < n && p[i] == '}') return i + 1;
        while (true) {
            while (i < n && json_ws(p[i])) i++;
            if (i >= n || p[i] != '"') return -1;
            i = scan_json_string(p, n, i);
            if (i < 0) return -1;
            while (i < n && json_ws(p[i])) i++;
            if (i >= n || p[i] != ':') return -1;
            i = scan_json_value(p, n, i + 1, depth - 1);
            if (i < 0) return -1;
            while (i < n && json_ws(p[i])) i++;
            if (i < n && p[i] == ',') {
                i++;
                continue;
            }
            if (i < n && p[i] == '}') return i + 1;
            return -1;
        }
    }
    if (c == '[') {
        i++;
        while (i < n && json_ws(p[i])) i++;
        if (i < n && p[i] == ']') return i + 1;
        while (true) {
            i = scan_json_value(p, n, i, depth - 1);
            if (i < 0) return -1;
            while (i < n && json_ws(p[i])) i++;
            if (i < n && p[i] == ',') {
                i++;
                continue;
            }
            if (i < n && p[i] == ']') return i + 1;
            return -1;
        }
    }
    {
        int64_t e = scan_json_literal(p, n, i);
        if (e >= 0) return e;
    }
    if (c == '-' || (c >= '0' && c <= '9')) return scan_json_number(p, n, i);
    return -1;
}

// strict UTF-8 validity (no overlongs, no surrogates, max U+10FFFF).
// The render memo is keyed on CANONICAL path bytes — the UTF-8 encoding
// of the errors="replace" decode the oracle applies — and valid UTF-8
// is exactly the class where raw bytes == canonical bytes. Writes whose
// path components fail this defer to Python, whose invalidation
// canonicalises (native/engine.py uj_invalidate), so byte-distinct
// paths that decode identically can never leave a stale memo behind.
inline bool utf8_valid(const uint8_t* p, int64_t n) {
    int64_t i = 0;
    while (i < n) {
        uint8_t c = p[i];
        if (c < 0x80) {
            i++;
            continue;
        }
        int len;
        uint32_t cp;
        if ((c & 0xE0) == 0xC0) {
            len = 2;
            cp = c & 0x1F;
        } else if ((c & 0xF0) == 0xE0) {
            len = 3;
            cp = c & 0x0F;
        } else if ((c & 0xF8) == 0xF0) {
            len = 4;
            cp = c & 0x07;
        } else {
            return false;
        }
        if (i + len > n) return false;
        for (int j = 1; j < len; j++) {
            if ((p[i + j] & 0xC0) != 0x80) return false;
            cp = (cp << 6) | (p[i + j] & 0x3F);
        }
        if (len == 2 && cp < 0x80) return false;          // overlong
        if (len == 3 && cp < 0x800) return false;         // overlong
        if (len == 4 && cp < 0x10000) return false;       // overlong
        if (cp >= 0xD800 && cp <= 0xDFFF) return false;   // surrogate
        if (cp > 0x10FFFF) return false;
        i += len;
    }
    return true;
}

// INS/RM value: a JSON *primitive* (parse_value raises on containers)
inline bool ujson_prim_ok(const uint8_t* p, int64_t n) {
    int64_t i = 0;
    while (i < n && json_ws(p[i])) i++;
    if (i >= n) return false;
    int64_t e;
    if (p[i] == '"') {
        e = scan_json_string(p, n, i);
    } else if ((e = scan_json_literal(p, n, i)) < 0) {
        e = scan_json_number(p, n, i);
    }
    if (e < 0) return false;
    while (e < n && json_ws(p[e])) e++;
    return e == n;
}

// SET value: any JSON document (parse_doc takes containers too)
inline bool ujson_doc_ok(const uint8_t* p, int64_t n) {
    int64_t e = scan_json_value(p, n, 0, 64);
    if (e < 0) return false;
    while (e < n && json_ws(p[e])) e++;
    return e == n;
}

// ---- UJSON write queue -----------------------------------------------------
//
// UJSON INS/SET/RM/CLR are applied by the ORACLE at queue-flush time
// (repo_ujson.py _flush_queue, which runs before any other UJSON work in
// arrival order — per-connection ordering and the observe-first
// delta/lattice semantics are exactly the reference's). The engine's job
// is validate-and-bank: prove the later apply cannot raise (the value
// validators above), record the raw argument slices, invalidate the
// overlapping render memos, and reply +OK.

struct UjsonQueue {
    // blob layout per command: u32 argc, then per arg u32 len + bytes
    std::vector<uint8_t> blob;
    int64_t count = 0;

    static constexpr int64_t MAX_CMDS = 65536;
    static constexpr size_t MAX_BYTES = 16u << 20;

    bool full() const {
        return count >= MAX_CMDS || blob.size() >= MAX_BYTES;
    }

    void push(const uint8_t* buf, const int64_t* offs, const int64_t* lens,
              int32_t argc) {
        uint32_t n = static_cast<uint32_t>(argc);
        const uint8_t* np = reinterpret_cast<const uint8_t*>(&n);
        blob.insert(blob.end(), np, np + 4);
        for (int32_t i = 0; i < argc; i++) {
            uint32_t ln = static_cast<uint32_t>(lens[i]);
            const uint8_t* lp = reinterpret_cast<const uint8_t*>(&ln);
            blob.insert(blob.end(), lp, lp + 4);
            blob.insert(blob.end(), buf + offs[i], buf + offs[i] + lens[i]);
        }
        count++;
    }

    void clear() {
        blob.clear();
        count = 0;
    }
};

// ---- the engine ------------------------------------------------------------

struct Engine {
    Table t[2];  // 0 = GCOUNT, 1 = PNCOUNT
    TregTable treg;
    TlogTable tlog;
    UjsonQueue uq;
    UjsonTable uj;
    // commands settled natively, per type (G, PN, TREG, TLOG, UJSON) —
    // reads included; deferred commands count on the Python side instead
    // (models/manager.py _apply_core's per-Database tally). SYSTEM
    // METRICS reports the sum.
    uint64_t served[5] = {0, 0, 0, 0, 0};
};

// ---- shared formatting / parsing helpers -----------------------------------

inline int64_t digits10(uint64_t v) {
    int64_t n = 1;
    while (v >= 10) {
        v /= 10;
        n++;
    }
    return n;
}

inline int64_t fmt_u64(uint8_t* out, uint64_t v) {
    char tmp[24];
    int n = 0;
    do {
        tmp[n++] = static_cast<char>('0' + v % 10);
        v /= 10;
    } while (v);
    for (int i = 0; i < n; i++) out[i] = static_cast<uint8_t>(tmp[n - 1 - i]);
    return n;
}

inline int64_t fmt_int_reply(uint8_t* out, uint64_t bits, bool signed_i64) {
    int64_t n = 0;
    out[n++] = ':';
    if (signed_i64 && static_cast<int64_t>(bits) < 0) {
        out[n++] = '-';
        bits = ~bits + 1;  // unsigned-domain negate: defined for INT64_MIN
    }
    n += fmt_u64(out + n, bits);
    out[n++] = '\r';
    out[n++] = '\n';
    return n;
}

// strict u64 parse: ASCII digits only, must fit (models/base.py parse_u64)
inline bool parse_amount(const uint8_t* s, int64_t n, uint64_t* out) {
    if (n <= 0) return false;
    uint64_t v = 0;
    for (int64_t i = 0; i < n; i++) {
        if (s[i] < '0' || s[i] > '9') return false;
        uint64_t d = static_cast<uint64_t>(s[i] - '0');
        if (v > (UINT64_MAX - d) / 10) return false;
        v = v * 10 + d;
    }
    *out = v;
    return true;
}

inline bool word_is(const uint8_t* buf, int64_t off, int64_t len,
                    const char* w) {
    int64_t n = static_cast<int64_t>(strlen(w));
    return len == n && memcmp(buf + off, w, static_cast<size_t>(n)) == 0;
}

}  // namespace jy
