// Native serving engine v2 — the all-types command hot path.
//
// Extends the counter engine (counter_engine.cpp) to the full command
// mix the reference serves from compiled actors on every core
// (jylis/server_notify.pony:8-36): TREG SET/GET, TLOG INS/SIZE/GET/CUTOFF,
// UJSON GET (from the per-key render memo) and the validated UJSON
// INS/SET/RM/CLR write queue settle here, so a pipelined burst of
// mixed traffic makes ONE FFI call instead of one interpreter dispatch
// per command. TLOG TRIM/TRIMAT/CLR stay with Python: they dispatch a
// device drain. Table semantics live in engine.h; models/treg_table.py
// and models/tlog_table.py hold the pure-Python oracles, and
// differential tests pin the equivalence.

#include "engine.h"

using namespace jy;

namespace {

// pending-rows thresholds past which writes bounce so the Python repo
// runs its device drain (must match repo_treg.py PENDING_DRAIN_THRESHOLD
// and repo_tlog.py ROW/PENDING_DRAIN_THRESHOLD — pinned by
// tests/test_serve_tables.py)
constexpr int64_t TREG_PENDING_DRAIN = 4096;

}  // namespace

extern "C" {

// ---- TREG ------------------------------------------------------------------

int64_t jy_treg_rows(void* e) {
    return static_cast<Engine*>(e)->treg.idx.rows();
}

int64_t jy_treg_upsert(void* e, const uint8_t* k, int64_t n) {
    return static_cast<Engine*>(e)->treg.upsert(k, n);
}

int64_t jy_treg_find(void* e, const uint8_t* k, int64_t n) {
    return static_cast<Engine*>(e)->treg.idx.find(k, n);
}

void jy_treg_key(void* e, int64_t row, const uint8_t** ptr, int64_t* len) {
    TregTable& t = static_cast<Engine*>(e)->treg;
    *ptr = t.idx.key_ptr(row);
    *len = t.idx.key_len[row];
}

void jy_treg_write(void* e, int64_t row, uint64_t ts, const uint8_t* v,
                   int64_t n) {
    static_cast<Engine*>(e)->treg.write(row, ts, v, n);
}

void jy_treg_note_delta(void* e, int64_t row, uint64_t ts, const uint8_t* v,
                        int64_t n) {
    static_cast<Engine*>(e)->treg.note_delta(row, ts, v, n);
}

int32_t jy_treg_winner(void* e, int64_t row, uint64_t* ts,
                       const uint8_t** ptr, int64_t* len) {
    TregTable& t = static_cast<Engine*>(e)->treg;
    const std::string* val;
    if (!t.winner(row, ts, &val)) return 0;
    *ptr = reinterpret_cast<const uint8_t*>(val->data());
    *len = static_cast<int64_t>(val->size());
    return 1;
}

int64_t jy_treg_pend_count(void* e) {
    return static_cast<int64_t>(
        static_cast<Engine*>(e)->treg.pend_rows.size());
}

int64_t jy_treg_export_pend(void* e, int64_t* rows, uint64_t* ts,
                            int64_t cap) {
    TregTable& t = static_cast<Engine*>(e)->treg;
    int64_t n = static_cast<int64_t>(t.pend_rows.size());
    if (n > cap) return -n;
    for (int64_t i = 0; i < n; i++) {
        rows[i] = t.pend_rows[i];
        ts[i] = t.pend_ts[t.pend_rows[i]];
    }
    return n;
}

void jy_treg_pend_val(void* e, int64_t row, const uint8_t** ptr,
                      int64_t* len) {
    TregTable& t = static_cast<Engine*>(e)->treg;
    *ptr = reinterpret_cast<const uint8_t*>(t.pend_val[row].data());
    *len = static_cast<int64_t>(t.pend_val[row].size());
}

void jy_treg_fold_pend(void* e) { static_cast<Engine*>(e)->treg.fold_pending(); }

int64_t jy_treg_delta_count(void* e) {
    return static_cast<int64_t>(
        static_cast<Engine*>(e)->treg.delta_rows.size());
}

int64_t jy_treg_export_deltas(void* e, int64_t* rows, uint64_t* ts,
                              int64_t cap) {
    TregTable& t = static_cast<Engine*>(e)->treg;
    int64_t n = static_cast<int64_t>(t.delta_rows.size());
    if (n > cap) return -n;
    for (int64_t i = 0; i < n; i++) {
        rows[i] = t.delta_rows[i];
        ts[i] = t.delta_ts[t.delta_rows[i]];
    }
    return n;
}

void jy_treg_delta_val(void* e, int64_t row, const uint8_t** ptr,
                       int64_t* len) {
    TregTable& t = static_cast<Engine*>(e)->treg;
    *ptr = reinterpret_cast<const uint8_t*>(t.delta_val[row].data());
    *len = static_cast<int64_t>(t.delta_val[row].size());
}

// bulk delta export (the heartbeat flush hot path): sizes first, then
// ONE call fills every per-row array and both byte blobs — per-row FFI
// round-trips made the 100k-key flush ~12x slower than the dict oracle
void jy_treg_deltas_info(void* e, int64_t* n, int64_t* val_bytes,
                         int64_t* key_bytes) {
    TregTable& t = static_cast<Engine*>(e)->treg;
    *n = static_cast<int64_t>(t.delta_rows.size());
    int64_t vb = 0, kb = 0;
    for (int64_t row : t.delta_rows) {
        vb += static_cast<int64_t>(t.delta_val[row].size());
        kb += t.idx.key_len[row];
    }
    *val_bytes = vb;
    *key_bytes = kb;
}

void jy_treg_export_deltas_bulk(void* e, uint64_t* ts, int64_t* val_off,
                                int64_t* val_len, uint8_t* val_blob,
                                int64_t* key_off, int64_t* key_len,
                                uint8_t* key_blob) {
    TregTable& t = static_cast<Engine*>(e)->treg;
    int64_t vpos = 0, kpos = 0;
    for (size_t i = 0; i < t.delta_rows.size(); i++) {
        int64_t row = t.delta_rows[i];
        ts[i] = t.delta_ts[row];
        const std::string& v = t.delta_val[row];
        val_off[i] = vpos;
        val_len[i] = static_cast<int64_t>(v.size());
        memcpy(val_blob + vpos, v.data(), v.size());
        vpos += static_cast<int64_t>(v.size());
        key_off[i] = kpos;
        key_len[i] = t.idx.key_len[row];
        memcpy(key_blob + kpos, t.idx.key_ptr(row),
               static_cast<size_t>(t.idx.key_len[row]));
        kpos += t.idx.key_len[row];
    }
}

int64_t jy_treg_export_sync_dirty(void* e, int64_t* rows, int64_t cap) {
    TregTable& t = static_cast<Engine*>(e)->treg;
    int64_t n = static_cast<int64_t>(t.sync_dirty.size());
    if (n > cap) return -n;
    for (int64_t i = 0; i < n; i++) {
        rows[i] = t.sync_dirty[i];
        t.sync_flag[t.sync_dirty[i]] = 0;
    }
    t.sync_dirty.clear();
    return n;
}

void jy_treg_clear_deltas(void* e) {
    TregTable& t = static_cast<Engine*>(e)->treg;
    for (int64_t row : t.delta_rows) {
        t.delta_set[row] = 0;
        t.delta_val[row].clear();
    }
    t.delta_rows.clear();
}

// ---- TLOG ------------------------------------------------------------------

int64_t jy_tlog_rows(void* e) {
    return static_cast<Engine*>(e)->tlog.idx.rows();
}

int64_t jy_tlog_upsert(void* e, const uint8_t* k, int64_t n) {
    return static_cast<Engine*>(e)->tlog.upsert(k, n);
}

int64_t jy_tlog_find(void* e, const uint8_t* k, int64_t n) {
    return static_cast<Engine*>(e)->tlog.idx.find(k, n);
}

void jy_tlog_key(void* e, int64_t row, const uint8_t** ptr, int64_t* len) {
    TlogTable& t = static_cast<Engine*>(e)->tlog;
    *ptr = t.idx.key_ptr(row);
    *len = t.idx.key_len[row];
}

void jy_tlog_ins(void* e, int64_t row, uint64_t ts, const uint8_t* v,
                 int64_t n) {
    static_cast<Engine*>(e)->tlog.ins(row, ts, v, n);
}

void jy_tlog_conv_entry(void* e, int64_t row, uint64_t ts, const uint8_t* v,
                        int64_t n) {
    static_cast<Engine*>(e)->tlog.converge_entry(row, ts, v, n);
}

void jy_tlog_conv_cutoff(void* e, int64_t row, uint64_t c) {
    static_cast<Engine*>(e)->tlog.raise_pend_cutoff(row, c);
}

int64_t jy_tlog_size(void* e, int64_t row) {
    return static_cast<Engine*>(e)->tlog.size(row);
}

int64_t jy_tlog_len_cache(void* e, int64_t row) {
    return static_cast<Engine*>(e)->tlog.rows[row].len_cache;
}

uint64_t jy_tlog_cut_cache(void* e, int64_t row) {
    return static_cast<Engine*>(e)->tlog.rows[row].cut_cache;
}

uint64_t jy_tlog_cutoff_view(void* e, int64_t row) {
    TlogTable& t = static_cast<Engine*>(e)->tlog;
    return t.cutoff_view(t.rows[row]);
}

uint64_t jy_tlog_pend_cutoff(void* e, int64_t row) {
    return static_cast<Engine*>(e)->tlog.rows[row].pend_cutoff;
}

int32_t jy_tlog_quiescent(void* e, int64_t row) {
    TlogTable& t = static_cast<Engine*>(e)->tlog;
    return t.quiescent(t.rows[row]) ? 1 : 0;
}

uint64_t jy_tlog_gen(void* e, int64_t row) {
    return static_cast<Engine*>(e)->tlog.rows[row].gen;
}

int64_t jy_tlog_pend_len(void* e, int64_t row) {
    return static_cast<int64_t>(
        static_cast<Engine*>(e)->tlog.rows[row].pend.size());
}

int64_t jy_tlog_pend_rows_count(void* e) {
    return static_cast<Engine*>(e)->tlog.pend_rows_count;
}

int32_t jy_tlog_row_overdue(void* e) {
    return static_cast<Engine*>(e)->tlog.row_overdue ? 1 : 0;
}

// rows with pending entries OR a pending cutoff — the drain's row set,
// maintained as an insertion-deduped list (O(touched), not O(rows))
int64_t jy_tlog_touched_rows(void* e, int64_t* out, int64_t cap) {
    TlogTable& t = static_cast<Engine*>(e)->tlog;
    int64_t n = static_cast<int64_t>(t.touched_list.size());
    if (n > cap) return -n;
    for (int64_t i = 0; i < n; i++) out[i] = t.touched_list[i];
    return n;
}

int64_t jy_tlog_touched_count(void* e) {
    return static_cast<int64_t>(
        static_cast<Engine*>(e)->tlog.touched_list.size());
}

// the drained row content when the carried base is valid; the
// unavailable sentinel otherwise (repo gathers from the device instead)
int64_t jy_tlog_export_base(void* e, int64_t row, uint64_t* ts, int32_t* vid,
                            int64_t cap) {
    TlogRow& r = static_cast<Engine*>(e)->tlog.rows[row];
    if (!r.base_valid) return -1 - (int64_t(1) << 40);
    int64_t n = static_cast<int64_t>(r.base.size());
    if (n > cap) return -n;
    for (int64_t i = 0; i < n; i++) {
        ts[i] = r.base[i].ts;
        vid[i] = r.base[i].vid;
    }
    return n;
}

// bulk delta export (the heartbeat flush hot path; see the TREG analog)
void jy_tlog_deltas_info(void* e, int64_t* n, int64_t* total_entries,
                         int64_t* key_bytes) {
    TlogTable& t = static_cast<Engine*>(e)->tlog;
    *n = static_cast<int64_t>(t.delta_rows.size());
    int64_t te = 0, kb = 0;
    for (int64_t row : t.delta_rows) {
        te += static_cast<int64_t>(t.rows[row].delta.size());
        kb += t.idx.key_len[row];
    }
    *total_entries = te;
    *key_bytes = kb;
}

void jy_tlog_export_deltas_bulk(void* e, int64_t* counts, uint64_t* cutoffs,
                                uint64_t* ts_flat, int32_t* vid_flat,
                                int64_t* key_off, int64_t* key_len,
                                uint8_t* key_blob) {
    TlogTable& t = static_cast<Engine*>(e)->tlog;
    int64_t epos = 0, kpos = 0;
    for (size_t i = 0; i < t.delta_rows.size(); i++) {
        int64_t row = t.delta_rows[i];
        const TlogRow& r = t.rows[row];
        counts[i] = static_cast<int64_t>(r.delta.size());
        cutoffs[i] = r.delta_cutoff;
        for (const TlogEnt& en : r.delta) {
            ts_flat[epos] = en.ts;
            vid_flat[epos] = en.vid;
            epos++;
        }
        key_off[i] = kpos;
        key_len[i] = t.idx.key_len[row];
        memcpy(key_blob + kpos, t.idx.key_ptr(row),
               static_cast<size_t>(t.idx.key_len[row]));
        kpos += t.idx.key_len[row];
    }
}

// bulk pending export for the device drain: counts + flat entry arrays
// for the given row set in ONE call
int64_t jy_tlog_export_pend_bulk(void* e, const int64_t* rows, int64_t nrows,
                                 int64_t* counts, uint64_t* ts_flat,
                                 int32_t* vid_flat, int64_t cap) {
    TlogTable& t = static_cast<Engine*>(e)->tlog;
    int64_t total = 0;
    for (int64_t i = 0; i < nrows; i++)
        total += static_cast<int64_t>(t.rows[rows[i]].pend.size());
    if (total > cap) return -total;
    int64_t epos = 0;
    for (int64_t i = 0; i < nrows; i++) {
        const TlogRow& r = t.rows[rows[i]];
        counts[i] = static_cast<int64_t>(r.pend.size());
        for (const TlogEnt& en : r.pend) {
            ts_flat[epos] = en.ts;
            vid_flat[epos] = en.vid;
            epos++;
        }
    }
    return total;
}

// bulk value resolution: every interned string from `lo` up in one call
// (the Python vid->bytes mirror refills after compaction with two calls
// instead of one per vid)
void jy_tlog_vals_info(void* e, int32_t lo, int64_t* n, int64_t* bytes_) {
    TlogTable& t = static_cast<Engine*>(e)->tlog;
    int64_t total = 0;
    for (size_t i = lo; i < t.vals.size(); i++)
        total += static_cast<int64_t>(t.vals[i].size());
    *n = static_cast<int64_t>(t.vals.size()) - lo;
    *bytes_ = total;
}

void jy_tlog_export_vals(void* e, int32_t lo, int64_t* off, int64_t* len,
                         uint8_t* blob) {
    TlogTable& t = static_cast<Engine*>(e)->tlog;
    int64_t pos = 0;
    for (size_t i = lo; i < t.vals.size(); i++) {
        const std::string& v = t.vals[i];
        off[i - lo] = pos;
        len[i - lo] = static_cast<int64_t>(v.size());
        memcpy(blob + pos, v.data(), v.size());
        pos += static_cast<int64_t>(v.size());
    }
}

int64_t jy_tlog_export_sync_dirty(void* e, int64_t* rows, int64_t cap) {
    TlogTable& t = static_cast<Engine*>(e)->tlog;
    int64_t n = static_cast<int64_t>(t.sync_dirty.size());
    if (n > cap) return -n;
    for (int64_t i = 0; i < n; i++) {
        rows[i] = t.sync_dirty[i];
        t.rows[t.sync_dirty[i]].sync_flag = false;
    }
    t.sync_dirty.clear();
    return n;
}

int32_t jy_tlog_compact(void* e) {
    return static_cast<Engine*>(e)->tlog.compact_values() ? 1 : 0;
}

int32_t jy_tlog_base_valid(void* e, int64_t row) {
    return static_cast<Engine*>(e)->tlog.rows[row].base_valid ? 1 : 0;
}

int64_t jy_tlog_live_total(void* e) {
    return static_cast<Engine*>(e)->tlog.live_total;
}

int64_t jy_tlog_export_pend(void* e, int64_t row, uint64_t* ts, int32_t* vid,
                            int64_t cap) {
    TlogRow& r = static_cast<Engine*>(e)->tlog.rows[row];
    int64_t n = static_cast<int64_t>(r.pend.size());
    if (n > cap) return -n;
    for (int64_t i = 0; i < n; i++) {
        ts[i] = r.pend[i].ts;
        vid[i] = r.pend[i].vid;
    }
    return n;
}

void jy_tlog_val(void* e, int32_t vid, const uint8_t** ptr, int64_t* len) {
    TlogTable& t = static_cast<Engine*>(e)->tlog;
    *ptr = reinterpret_cast<const uint8_t*>(t.vals[vid].data());
    *len = static_cast<int64_t>(t.vals[vid].size());
}

int32_t jy_tlog_intern(void* e, const uint8_t* v, int64_t n) {
    return static_cast<Engine*>(e)->tlog.intern(v, n);
}

void jy_tlog_finish_row(void* e, int64_t row, int64_t len, uint64_t cut) {
    static_cast<Engine*>(e)->tlog.finish_drain_row(row, len, cut);
}

void jy_tlog_finish_end(void* e) {
    static_cast<Engine*>(e)->tlog.finish_drain_end();
}

void jy_tlog_set_base(void* e, int64_t row, int64_t n, const uint64_t* ts,
                      const int32_t* vid) {
    TlogRow& r = static_cast<Engine*>(e)->tlog.rows[row];
    r.base.clear();
    r.base.reserve(n);
    for (int64_t i = 0; i < n; i++) r.base.push_back(TlogEnt{ts[i], vid[i]});
    r.base_valid = true;
    r.memo_valid = false;
    r.memo.clear();
    r.gen++;
}

// memo export; caller must have just called jy_tlog_size (>= 0) under the
// repo lock, so the memo is current (or the row quiescent, in which case
// the memo may be absent and the BASE is the view)
int64_t jy_tlog_export_merged(void* e, int64_t row, uint64_t* ts,
                              int32_t* vid, int64_t cap) {
    TlogTable& t = static_cast<Engine*>(e)->tlog;
    TlogRow& r = t.rows[row];
    if (t.memo_current(r)) {
        int64_t n = static_cast<int64_t>(r.memo.size());
        if (n > cap) return -n;
        int64_t i = 0;
        for (const TlogEnt& en : r.memo) {
            ts[i] = en.ts;
            vid[i] = en.vid;
            i++;
        }
        return n;
    }
    if (t.quiescent(r) && r.base_valid) {
        int64_t n = static_cast<int64_t>(r.base.size());
        if (n > cap) return -n;
        for (int64_t i = 0; i < n; i++) {
            ts[i] = r.base[i].ts;
            vid[i] = r.base[i].vid;
        }
        return n;
    }
    return -1 - (int64_t(1) << 40);  // unavailable sentinel
}

int64_t jy_tlog_delta_rows_count(void* e) {
    return static_cast<int64_t>(
        static_cast<Engine*>(e)->tlog.delta_rows.size());
}

int64_t jy_tlog_export_delta_rows(void* e, int64_t* out, int64_t cap) {
    TlogTable& t = static_cast<Engine*>(e)->tlog;
    int64_t n = static_cast<int64_t>(t.delta_rows.size());
    if (n > cap) return -n;
    for (int64_t i = 0; i < n; i++) out[i] = t.delta_rows[i];
    return n;
}

int64_t jy_tlog_export_delta(void* e, int64_t row, uint64_t* ts, int32_t* vid,
                             int64_t cap) {
    TlogRow& r = static_cast<Engine*>(e)->tlog.rows[row];
    int64_t n = static_cast<int64_t>(r.delta.size());
    if (n > cap) return -n;
    int64_t i = 0;
    for (const TlogEnt& en : r.delta) {
        ts[i] = en.ts;
        vid[i] = en.vid;
        i++;
    }
    return n;
}

uint64_t jy_tlog_delta_cutoff(void* e, int64_t row) {
    return static_cast<Engine*>(e)->tlog.rows[row].delta_cutoff;
}

// hostref.TLog.raise_cutoff on the delta accumulator, creating it like
// repo_tlog.py _delta_for does
void jy_tlog_delta_raise_cutoff(void* e, int64_t row, uint64_t c) {
    TlogTable& t = static_cast<Engine*>(e)->tlog;
    TlogRow& r = t.rows[row];
    if (!r.delta_present) {
        r.delta_present = true;
        t.delta_rows.push_back(row);
    }
    if (c > r.delta_cutoff) {
        r.delta_cutoff = c;
        for (auto it = r.delta.begin(); it != r.delta.end();)
            it = it->ts < c ? r.delta.erase(it) : std::next(it);
    }
}

void jy_tlog_clear_deltas(void* e) {
    TlogTable& t = static_cast<Engine*>(e)->tlog;
    for (int64_t row : t.delta_rows) {
        TlogRow& r = t.rows[row];
        r.delta_present = false;
        r.delta.clear();
        r.delta_cutoff = 0;  // a fresh hostref.TLog after every flush
    }
    t.delta_rows.clear();
}

// commands settled natively since startup, per type (G, PN, TREG, TLOG,
// UJSON) — the SYSTEM METRICS "cmds" surface's native half
void jy_eng_served(void* e, uint64_t* out) {
    Engine* eng = static_cast<Engine*>(e);
    for (int i = 0; i < 5; i++) out[i] = eng->served[i];
}

// ---- UJSON queue -----------------------------------------------------------

int64_t jy_uq_count(void* e) { return static_cast<Engine*>(e)->uq.count; }

int64_t jy_uq_bytes(void* e) {
    return static_cast<int64_t>(static_cast<Engine*>(e)->uq.blob.size());
}

int64_t jy_uq_data(void* e, uint8_t* out, int64_t cap) {
    UjsonQueue& q = static_cast<Engine*>(e)->uq;
    int64_t n = static_cast<int64_t>(q.blob.size());
    if (n > cap) return -n;
    memcpy(out, q.blob.data(), static_cast<size_t>(n));
    return n;
}

void jy_uq_clear(void* e) { static_cast<Engine*>(e)->uq.clear(); }

// ---- UJSON render memo (engine.h UjsonTable) -------------------------------

int64_t jy_uj_upsert(void* e, const uint8_t* k, int64_t n) {
    return static_cast<Engine*>(e)->uj.upsert(k, n);
}

void jy_uj_memo_put(void* e, int64_t row, const uint8_t* path, int64_t pn,
                    const uint8_t* reply, int64_t rn) {
    static_cast<Engine*>(e)->uj.put(
        row, std::string(reinterpret_cast<const char*>(path),
                         static_cast<size_t>(pn)),
        std::string(reinterpret_cast<const char*>(reply),
                    static_cast<size_t>(rn)));
}

void jy_uj_invalidate(void* e, const uint8_t* k, int64_t n,
                      const uint8_t* path, int64_t pn, int32_t subtree) {
    UjsonTable& u = static_cast<Engine*>(e)->uj;
    int64_t row = u.idx.find(k, n);
    if (row >= 0)
        u.invalidate(row,
                     std::string(reinterpret_cast<const char*>(path),
                                 static_cast<size_t>(pn)),
                     subtree != 0);
}

int64_t jy_uj_memo_len(void* e, const uint8_t* k, int64_t n) {
    UjsonTable& u = static_cast<Engine*>(e)->uj;
    int64_t row = u.idx.find(k, n);
    return row < 0 ? 0 : static_cast<int64_t>(u.memo[row].size());
}

// ---- the batch applier -----------------------------------------------------
//
// Returns:
//   0  consumed all complete commands (tail incomplete or buffer empty)
//   1  stopped at a command Python must apply: its slices are in
//      offs/lens/n_args and *consumed INCLUDES it
//   2  reply buffer nearly full: flush replies and call again
//  -1  protocol error at the stop point (serve replies, drop connection)
//  -2  a command has more than max_args arguments (grow and retry)
// changed[5] counts state-changing applies per type
// (G, PN, TREG, TLOG, UJSON) for the caller's on-change notifications.
int32_t jy_eng_scan_apply2(void* ev, const uint8_t* buf, int64_t len,
                           uint8_t* out, int64_t out_cap, int64_t* out_len,
                           int64_t* consumed, int64_t* offs, int64_t* lens,
                           int32_t max_args, int32_t* n_args,
                           int32_t* changed) {
    Engine* eng = static_cast<Engine*>(ev);
    *out_len = 0;
    *consumed = 0;
    *n_args = 0;
    for (int i = 0; i < 5; i++) changed[i] = 0;
    while (true) {
        if (out_cap - *out_len < 64) return 2;
        int64_t sub_consumed = 0;
        int32_t argc = 0;
        int32_t rc = resp_scan(buf + *consumed, len - *consumed, &sub_consumed,
                               offs, lens, max_args, &argc);
        if (rc == 0) return 0;
        if (rc == -1) return -1;
        if (rc == -2) {
            *n_args = argc;
            return -2;
        }
        for (int32_t i = 0; i < argc; i++) offs[i] += *consumed;
        bool inline_blank = argc == 0 && buf[*consumed] != '*';
        if (inline_blank) {  // oracle parser skips blank inline lines
            *consumed += sub_consumed;
            continue;
        }
        // bounce THIS command to the Python path, consumed
        auto defer = [&]() -> int32_t {
            *n_args = argc;
            *consumed += sub_consumed;
            return 1;
        };

        // ---- counters (exact round-3 semantics) ---------------------------
        int32_t which = -1;
        if (argc >= 1 && word_is(buf, offs[0], lens[0], "GCOUNT")) which = 0;
        if (argc >= 1 && word_is(buf, offs[0], lens[0], "PNCOUNT")) which = 1;
        if (which >= 0) {
            Table& t = eng->t[which];
            // GET key — reply from the value cache unless foreign-dirty
            if (argc >= 3 && word_is(buf, offs[1], lens[1], "GET")) {
                int64_t row = t.find(buf + offs[2], lens[2]);
                if (row >= 0 && (t.flags[row] & F_FOREIGN))
                    return defer();  // Python drains and serves this one
                uint64_t v = row >= 0 ? t.value[row] : 0;
                *out_len += fmt_int_reply(out + *out_len, v, which == 1);
                eng->served[which]++;
                *consumed += sub_consumed;
                continue;
            }
            int polarity = -1;
            if (argc >= 4 && word_is(buf, offs[1], lens[1], "INC"))
                polarity = 0;
            if (which == 1 && argc >= 4 &&
                word_is(buf, offs[1], lens[1], "DEC"))
                polarity = 1;
            if (polarity >= 0) {
                uint64_t amount = 0;
                if (!parse_amount(buf + offs[3], lens[3], &amount))
                    return defer();  // ParseError -> help text, Python's job
                int64_t row = t.upsert(buf + offs[2], lens[2]);
                t.bump(row, polarity, amount);
                changed[which]++;
                eng->served[which]++;
                memcpy(out + *out_len, "+OK\r\n", 5);
                *out_len += 5;
                *consumed += sub_consumed;
                continue;
            }
            return defer();  // unknown subcommand / wrong arity -> help
        }

        // ---- TREG ---------------------------------------------------------
        if (argc >= 1 && word_is(buf, offs[0], lens[0], "TREG")) {
            TregTable& t = eng->treg;
            if (argc >= 3 && word_is(buf, offs[1], lens[1], "GET")) {
                int64_t row = t.idx.find(buf + offs[2], lens[2]);
                uint64_t ts = 0;
                const std::string* val = nullptr;
                if (row < 0 || !t.winner(row, &ts, &val)) {
                    memcpy(out + *out_len, "$-1\r\n", 5);
                    *out_len += 5;
                    eng->served[2]++;
                    *consumed += sub_consumed;
                    continue;
                }
                int64_t need =
                    static_cast<int64_t>(val->size()) + 64;  // headers + ts
                if (out_cap - *out_len < need) {
                    if (*out_len > 0) return 2;  // flush replies, re-enter
                    return defer();  // value alone outgrows the buffer
                }
                uint8_t* o = out + *out_len;
                int64_t n = 0;
                memcpy(o + n, "*2\r\n$", 5);
                n += 5;
                n += fmt_u64(o + n, val->size());
                o[n++] = '\r';
                o[n++] = '\n';
                memcpy(o + n, val->data(), val->size());
                n += static_cast<int64_t>(val->size());
                o[n++] = '\r';
                o[n++] = '\n';
                n += fmt_int_reply(o + n, ts, false);
                *out_len += n;
                eng->served[2]++;
                *consumed += sub_consumed;
                continue;
            }
            if (argc >= 5 && word_is(buf, offs[1], lens[1], "SET")) {
                uint64_t ts = 0;
                if (!parse_amount(buf + offs[4], lens[4], &ts))
                    return defer();  // ParseError -> help
                // the write about to land would tip the drain threshold:
                // Python's may_drain path must run it (threaded drain)
                if (static_cast<int64_t>(t.pend_rows.size()) + 1 >=
                    TREG_PENDING_DRAIN)
                    return defer();
                int64_t row = t.upsert(buf + offs[2], lens[2]);
                t.write(row, ts, buf + offs[3], lens[3]);
                t.note_delta(row, ts, buf + offs[3], lens[3]);
                changed[2]++;
                eng->served[2]++;
                memcpy(out + *out_len, "+OK\r\n", 5);
                *out_len += 5;
                *consumed += sub_consumed;
                continue;
            }
            return defer();
        }

        // ---- TLOG ---------------------------------------------------------
        if (argc >= 1 && word_is(buf, offs[0], lens[0], "TLOG")) {
            TlogTable& t = eng->tlog;
            if (argc >= 3 && word_is(buf, offs[1], lens[1], "CUTOFF")) {
                int64_t row = t.idx.find(buf + offs[2], lens[2]);
                uint64_t c = row < 0 ? 0 : t.cutoff_view(t.rows[row]);
                *out_len += fmt_int_reply(out + *out_len, c, false);
                eng->served[3]++;
                *consumed += sub_consumed;
                continue;
            }
            if (argc >= 3 && word_is(buf, offs[1], lens[1], "GET")) {
                int64_t row = t.idx.find(buf + offs[2], lens[2]);
                if (row < 0) {
                    memcpy(out + *out_len, "*0\r\n", 4);
                    *out_len += 4;
                    eng->served[3]++;
                    *consumed += sub_consumed;
                    continue;
                }
                // optional count: any missing/unparseable value means
                // "all" (base.py parse_opt_count; repo_tlog.pony:49-50)
                uint64_t count = UINT64_MAX;
                if (argc >= 4 &&
                    !parse_amount(buf + offs[3], lens[3], &count))
                    count = UINT64_MAX;
                const std::vector<TlogEnt>* view = t.sorted_view_of(row);
                if (view == nullptr)
                    return defer();  // device row render: Python's job
                uint64_t n = static_cast<uint64_t>(view->size()) < count
                                 ? view->size()
                                 : count;
                int64_t need = 1 + digits10(n) + 2;
                for (uint64_t i = 0; i < n; i++) {
                    const TlogEnt& en = (*view)[i];
                    const std::string& v = t.vals[en.vid];
                    need += 4 + 1 + digits10(v.size()) + 2 +
                            static_cast<int64_t>(v.size()) + 2 + 1 +
                            digits10(en.ts) + 2;
                }
                if (out_cap - *out_len < need) {
                    if (*out_len > 0) return 2;  // flush replies, re-enter
                    return defer();  // reply alone outgrows the buffer
                }
                uint8_t* o = out + *out_len;
                int64_t m = 0;
                o[m++] = '*';
                m += fmt_u64(o + m, n);
                o[m++] = '\r';
                o[m++] = '\n';
                for (uint64_t i = 0; i < n; i++) {
                    const TlogEnt& en = (*view)[i];
                    const std::string& v = t.vals[en.vid];
                    memcpy(o + m, "*2\r\n$", 5);
                    m += 5;
                    m += fmt_u64(o + m, v.size());
                    o[m++] = '\r';
                    o[m++] = '\n';
                    memcpy(o + m, v.data(), v.size());
                    m += static_cast<int64_t>(v.size());
                    o[m++] = '\r';
                    o[m++] = '\n';
                    m += fmt_int_reply(o + m, en.ts, false);
                }
                *out_len += m;
                eng->served[3]++;
                *consumed += sub_consumed;
                continue;
            }
            if (argc >= 3 && word_is(buf, offs[1], lens[1], "SIZE")) {
                int64_t row = t.idx.find(buf + offs[2], lens[2]);
                int64_t n = row < 0 ? 0 : t.size(row);
                if (n < 0) return defer();  // drained base unknown
                *out_len += fmt_int_reply(out + *out_len,
                                          static_cast<uint64_t>(n), false);
                eng->served[3]++;
                *consumed += sub_consumed;
                continue;
            }
            if (argc >= 5 && word_is(buf, offs[1], lens[1], "INS")) {
                uint64_t ts = 0;
                if (!parse_amount(buf + offs[4], lens[4], &ts))
                    return defer();  // ParseError -> help
                int64_t row = t.idx.find(buf + offs[2], lens[2]);
                int64_t in_row =
                    row < 0 ? 0
                            : static_cast<int64_t>(t.rows[row].pend.size());
                // repo_tlog.py may_drain's exact predicate: Python must
                // run (and thread-offload) the drain this INS triggers
                if (in_row + 1 >= TlogTable::ROW_DRAIN_THRESHOLD ||
                    t.pend_rows_count + 1 >=
                        TlogTable::PENDING_DRAIN_THRESHOLD)
                    return defer();
                if (row < 0) row = t.upsert(buf + offs[2], lens[2]);
                t.ins(row, ts, buf + offs[3], lens[3]);
                changed[3]++;
                eng->served[3]++;
                memcpy(out + *out_len, "+OK\r\n", 5);
                *out_len += 5;
                *consumed += sub_consumed;
                continue;
            }
            return defer();
        }

        // ---- UJSON --------------------------------------------------------
        if (argc >= 1 && word_is(buf, offs[0], lens[0], "UJSON")) {
            UjsonTable& u = eng->uj;
            // path args [lo, hi) as the memo's length-prefixed blob key
            auto path_blob = [&](int32_t lo, int32_t hi) {
                std::string b;
                for (int32_t i = lo; i < hi; i++) {
                    uint32_t ln = static_cast<uint32_t>(lens[i]);
                    b.append(reinterpret_cast<const char*>(&ln), 4);
                    b.append(reinterpret_cast<const char*>(buf + offs[i]),
                             static_cast<size_t>(lens[i]));
                }
                return b;
            };
            // GET key [path...]: the oracle-rendered reply, memoised per
            // (key, path) and invalidated by every overlapping write — a
            // miss (or a never-rendered key) defers, and the Python GET
            // repairs the memo while serving (the TLOG base-repair shape)
            if (argc >= 3 && word_is(buf, offs[1], lens[1], "GET")) {
                int64_t row = u.idx.find(buf + offs[2], lens[2]);
                const std::string* reply =
                    row < 0 ? nullptr : u.get(row, path_blob(3, argc));
                if (reply == nullptr) return defer();
                int64_t need = static_cast<int64_t>(reply->size());
                if (out_cap - *out_len < need) {
                    if (*out_len > 0) return 2;  // flush replies, re-enter
                    return defer();  // reply alone outgrows the buffer
                }
                memcpy(out + *out_len, reply->data(), reply->size());
                *out_len += need;
                eng->served[4]++;
                *consumed += sub_consumed;
                continue;
            }
            // INS/SET/RM/CLR key [path...] [value]: validate that the
            // oracle's apply cannot raise, invalidate the overlapping
            // render memos, bank the raw slices, reply +OK (the oracle
            // applies the queue, in arrival order, before any other
            // UJSON work — repo_ujson.py _flush_queue)
            bool is_ins = argc >= 4 && word_is(buf, offs[1], lens[1], "INS");
            bool is_set = argc >= 4 && word_is(buf, offs[1], lens[1], "SET");
            bool is_rm = argc >= 4 && word_is(buf, offs[1], lens[1], "RM");
            bool is_clr = argc >= 3 && word_is(buf, offs[1], lens[1], "CLR");
            bool ok = is_clr;
            if (is_ins || is_rm)
                ok = ujson_prim_ok(buf + offs[argc - 1], lens[argc - 1]);
            else if (is_set)
                ok = ujson_doc_ok(buf + offs[argc - 1], lens[argc - 1]);
            // path components must be valid UTF-8 so the raw bytes ARE
            // the memo's canonical key (engine.h utf8_valid) — an
            // invalid component defers to Python, whose invalidation
            // canonicalises the path the same way the oracle decodes it
            if (ok) {
                int32_t path_end = is_clr ? argc : argc - 1;
                for (int32_t i = 3; ok && i < path_end; i++)
                    ok = utf8_valid(buf + offs[i], lens[i]);
            }
            if (ok && !eng->uq.full()) {
                int64_t row = u.idx.find(buf + offs[2], lens[2]);
                if (row >= 0)
                    u.invalidate(row, path_blob(3, is_clr ? argc : argc - 1),
                                 is_set || is_clr);
                eng->uq.push(buf, offs + 1, lens + 1, argc - 1);
                changed[4]++;
                eng->served[4]++;
                memcpy(out + *out_len, "+OK\r\n", 5);
                *out_len += 5;
                *consumed += sub_consumed;
                continue;
            }
            return defer();
        }

        return defer();  // any other first word: datatype help / SYSTEM
    }
}

}  // extern "C"
