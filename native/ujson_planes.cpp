// Native UJSON wire fast paths: split a PushDeltas body into per-key
// payload spans (the lazy WireUJSON receive path) and encode raw delta
// payloads straight into the packed device planes the resident store
// folds (jylis_tpu/ops/ujson_resident.py) — replica-id interning against
// the store's global columns and payload interning by canonical wire
// bytes happen here, so the per-delta Python cost on the anti-entropy
// hot path drops to array bookkeeping.
//
// Wire shape (cluster/codec.py _SCHEMA_TEXT, delta/UJSON):
//   entries: varint n, then per entry varint rid, varint seq,
//            varint n_path, n_path strings, token string
//   vv:      varint n, then per item varint rid, varint val
//   cloud:   varint n, then per item varint rid, varint seq
// (strings are varint-length-prefixed utf-8)
//
// Return conventions: 0 ok; -1 malformed; -2 value outside the requested
// layout (seq/col past the shift packing, vv past u32, varint past u64);
// -3 replica columns exceeded the vv plane width (caller grows and
// retries). The split validates utf-8 up front so that the Python-side
// lazy materialisation can never fail mid-serving.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Reader {
  const uint8_t* base;
  const uint8_t* p;
  const uint8_t* end;
  int rc = 0;  // sticky: 0 ok, -1 malformed, -2 unsupported

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (true) {
      if (p >= end) {
        rc = rc ? rc : -1;
        return 0;
      }
      uint8_t b = *p++;
      if (shift >= 64 && (b & 0x7f)) {
        rc = rc ? rc : -2;
        return 0;
      }
      if (shift == 63 && (b & 0x7e)) {
        rc = rc ? rc : -2;
        return 0;
      }
      if (shift < 64) v |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
      if (shift > 70) {
        rc = rc ? rc : -1;
        return 0;
      }
    }
  }
  int64_t count() {
    uint64_t v = varint();
    if (rc) return 0;
    if (v > static_cast<uint64_t>(end - p)) {
      rc = -1;
      return 0;
    }
    return static_cast<int64_t>(v);
  }
  int64_t bytes(int64_t* len_out) {
    uint64_t n = varint();
    if (rc) return 0;
    if (static_cast<uint64_t>(end - p) < n) {
      rc = -1;
      return 0;
    }
    int64_t off = p - base;
    p += n;
    *len_out = static_cast<int64_t>(n);
    return off;
  }
  bool done() const { return rc == 0 && p == end; }
};

bool utf8_valid(const uint8_t* s, int64_t n) {
  int64_t i = 0;
  while (i < n) {
    uint8_t b = s[i];
    if (b < 0x80) {
      i++;
    } else if ((b & 0xe0) == 0xc0) {
      if (i + 1 >= n || (s[i + 1] & 0xc0) != 0x80 || b < 0xc2) return false;
      i += 2;
    } else if ((b & 0xf0) == 0xe0) {
      if (i + 2 >= n || (s[i + 1] & 0xc0) != 0x80 || (s[i + 2] & 0xc0) != 0x80)
        return false;
      // reject overlongs and surrogates like Python's decoder does
      if (b == 0xe0 && s[i + 1] < 0xa0) return false;
      if (b == 0xed && s[i + 1] >= 0xa0) return false;
      i += 3;
    } else if ((b & 0xf8) == 0xf0) {
      if (i + 3 >= n || (s[i + 1] & 0xc0) != 0x80 ||
          (s[i + 2] & 0xc0) != 0x80 || (s[i + 3] & 0xc0) != 0x80)
        return false;
      if (b == 0xf0 && s[i + 1] < 0x90) return false;
      if (b > 0xf4 || (b == 0xf4 && s[i + 1] >= 0x90)) return false;
      i += 4;
    } else {
      return false;
    }
  }
  return true;
}

// walk one delta payload; optionally validate utf-8; track counts + max seq
void walk_delta(Reader& r, bool check_utf8, int64_t* n_entries,
                int64_t* n_vv, int64_t* n_cloud, uint64_t* max_seq) {
  uint64_t ms = 0;
  int64_t ne = r.count();
  for (int64_t i = 0; i < ne && !r.rc; i++) {
    r.varint();  // rid
    uint64_t seq = r.varint();
    if (seq > ms) ms = seq;
    int64_t np = r.count();
    for (int64_t j = 0; j <= np && !r.rc; j++) {
      int64_t len;
      int64_t off = r.bytes(&len);
      if (!r.rc && check_utf8 && !utf8_valid(r.base + off, len)) {
        r.rc = -2;  // the oracle raises CodecError; fast path declines
      }
    }
  }
  int64_t nv = r.count();
  for (int64_t i = 0; i < nv && !r.rc; i++) {
    r.varint();
    uint64_t v = r.varint();
    if (v > ms) ms = v;
  }
  int64_t nc = r.count();
  for (int64_t i = 0; i < nc && !r.rc; i++) {
    r.varint();
    uint64_t seq = r.varint();
    if (seq > ms) ms = seq;
  }
  *n_entries = ne;
  *n_vv = nv;
  *n_cloud = nc;
  *max_seq = ms;
}

// open-addressing u64 -> int32 map (replica-id interning)
struct U64Map {
  std::vector<uint64_t> keys;
  std::vector<int32_t> vals;
  std::vector<int64_t> slots;  // -1 empty

  explicit U64Map(int64_t expect) {
    int64_t cap = 16;
    while (cap < expect * 2) cap <<= 1;
    slots.assign(static_cast<size_t>(cap), -1);
  }
  static uint64_t hash(uint64_t k) {
    k ^= k >> 33;
    k *= 0xff51afd7ed558ccdULL;
    k ^= k >> 33;
    return k;
  }
  void grow() {
    std::vector<int64_t> ns(slots.size() * 2, -1);
    size_t m = ns.size() - 1;
    for (size_t i = 0; i < keys.size(); i++) {
      size_t s = hash(keys[i]) & m;
      while (ns[s] >= 0) s = (s + 1) & m;
      ns[s] = static_cast<int64_t>(i);
    }
    slots.swap(ns);
  }
  int32_t get_or_add(uint64_t k, bool* added) {
    size_t m = slots.size() - 1;
    size_t s = hash(k) & m;
    while (slots[s] >= 0) {
      if (keys[static_cast<size_t>(slots[s])] == k) {
        *added = false;
        return vals[static_cast<size_t>(slots[s])];
      }
      s = (s + 1) & m;
    }
    int32_t id = static_cast<int32_t>(keys.size());
    slots[s] = static_cast<int64_t>(keys.size());
    keys.push_back(k);
    vals.push_back(id);
    *added = true;
    if (keys.size() * 10 >= slots.size() * 7) grow();
    return id;
  }
};

// open-addressing byte-span -> int32 map (payload interning)
struct SpanMap {
  const uint8_t* base;
  std::vector<int64_t> offs;
  std::vector<int64_t> lens;
  std::vector<uint64_t> hashes;
  std::vector<int64_t> slots;

  explicit SpanMap(const uint8_t* b, int64_t expect) : base(b) {
    int64_t cap = 16;
    while (cap < expect * 2) cap <<= 1;
    slots.assign(static_cast<size_t>(cap), -1);
  }
  static uint64_t hash(const uint8_t* s, int64_t n) {
    uint64_t h = 1469598103934665603ULL;
    for (int64_t i = 0; i < n; i++) {
      h ^= s[i];
      h *= 1099511628211ULL;
    }
    return h;
  }
  void grow() {
    std::vector<int64_t> ns(slots.size() * 2, -1);
    size_t m = ns.size() - 1;
    for (size_t i = 0; i < offs.size(); i++) {
      size_t s = hashes[i] & m;
      while (ns[s] >= 0) s = (s + 1) & m;
      ns[s] = static_cast<int64_t>(i);
    }
    slots.swap(ns);
  }
  int32_t get_or_add(int64_t off, int64_t len) {
    uint64_t h = hash(base + off, len);
    size_t m = slots.size() - 1;
    size_t s = h & m;
    while (slots[s] >= 0) {
      size_t r = static_cast<size_t>(slots[s]);
      if (hashes[r] == h && lens[r] == len &&
          memcmp(base + offs[r], base + off, static_cast<size_t>(len)) == 0) {
        return static_cast<int32_t>(r);
      }
      s = (s + 1) & m;
    }
    int32_t id = static_cast<int32_t>(offs.size());
    slots[s] = static_cast<int64_t>(offs.size());
    offs.push_back(off);
    lens.push_back(len);
    hashes.push_back(h);
    if (offs.size() * 10 >= slots.size() * 7) grow();
    return id;
  }
};

}  // namespace

extern "C" {

// ---- PushDeltas body split (past tag + name) -------------------------------

int32_t jy_ujson_split_measure(const uint8_t* body, int64_t body_len,
                               int64_t* n_keys_out) {
  Reader r{body, body, body + body_len};
  int64_t n_keys = r.count();
  for (int64_t k = 0; k < n_keys && !r.rc; k++) {
    int64_t klen;
    r.bytes(&klen);
    int64_t ne, nv, nc;
    uint64_t ms;
    walk_delta(r, /*check_utf8=*/true, &ne, &nv, &nc, &ms);
  }
  if (r.rc) return r.rc;
  if (!r.done()) return -1;
  *n_keys_out = n_keys;
  return 0;
}

int32_t jy_ujson_split(const uint8_t* body, int64_t body_len, int64_t* key_off,
                       int64_t* key_len, int64_t* pay_off, int64_t* pay_len,
                       int64_t* n_entries, int64_t* n_vv, int64_t* n_cloud,
                       uint64_t* max_seq) {
  Reader r{body, body, body + body_len};
  int64_t n_keys = r.count();
  for (int64_t k = 0; k < n_keys && !r.rc; k++) {
    key_off[k] = r.bytes(&key_len[k]);
    int64_t start = r.p - r.base;
    walk_delta(r, /*check_utf8=*/false, &n_entries[k], &n_vv[k], &n_cloud[k],
               &max_seq[k]);
    pay_off[k] = start;
    pay_len[k] = (r.p - r.base) - start;
  }
  return r.rc;
}

// ---- wire -> device planes -------------------------------------------------
// Planes are pre-filled by the caller (dots/cloud with the layout's pad,
// pay with -1, vv with 0). dest_rows maps delta i to its plane row.

int32_t jy_ujson_grid_fill(
    const uint8_t* blob, int64_t n_deltas, const int64_t* d_off,
    const int64_t* d_len, const int64_t* dest_rows, int32_t shift, int64_t w,
    int64_t c, int64_t n_rep, const uint64_t* known_rids, int64_t n_known,
    void* dots_v, int32_t* pay, uint32_t* vv, void* cloud_v,
    uint64_t* new_rids_out, int64_t* n_new_out, int64_t* pay_span_off,
    int64_t* pay_span_len, int64_t* n_pays_out, int64_t* rids_seen_out) {
  const bool narrow = shift < 32;
  const uint64_t seq_cap = 1ULL << shift;
  const uint64_t col_cap = narrow ? (1ULL << (31 - shift))
                                  : 0x100000000ULL;
  const int32_t pad32 = 0x7fffffff;
  const uint64_t pad64 = 0xffffffffffffffffULL;
  int32_t* dots32 = static_cast<int32_t*>(dots_v);
  uint64_t* dots64 = static_cast<uint64_t*>(dots_v);
  int32_t* cloud32 = static_cast<int32_t*>(cloud_v);
  uint64_t* cloud64 = static_cast<uint64_t*>(cloud_v);

  U64Map rid_map(n_known + 64);
  for (int64_t i = 0; i < n_known; i++) {
    bool added;
    rid_map.get_or_add(known_rids[i], &added);
    if (!added) return -1;  // duplicate in the caller's column list
  }
  SpanMap pay_map(blob, 256);

  std::vector<std::pair<uint64_t, int32_t>> row;   // (packed, local pay)
  std::vector<uint64_t> crow;                      // packed cloud
  int rc_budget = 0;

  for (int64_t i = 0; i < n_deltas; i++) {
    Reader r{blob, blob + d_off[i], blob + d_off[i] + d_len[i]};
    int64_t base_row = dest_rows[i];
    row.clear();
    crow.clear();
    int64_t ne = r.count();
    if (ne > w) return -1;  // caller sized w from the measured counts
    for (int64_t e = 0; e < ne && !r.rc; e++) {
      uint64_t rid = r.varint();
      uint64_t seq = r.varint();
      int64_t span_start = r.p - r.base;
      int64_t np = r.count();
      int64_t len;
      for (int64_t j = 0; j <= np && !r.rc; j++) r.bytes(&len);
      if (r.rc) break;
      int64_t span_len = (r.p - r.base) - span_start;
      bool added;
      int32_t col = rid_map.get_or_add(rid, &added);
      // budget first: exceeding the vv plane is the caller's decision
      // (grow columns, maybe re-pack narrower) — keep walking so
      // rids_seen reports the full need
      if (col >= n_rep) {
        rc_budget = 1;
        continue;
      }
      if (static_cast<uint64_t>(col) >= col_cap) return -2;
      if (seq >= seq_cap || seq == 0xffffffffffffffffULL) return -2;
      uint64_t packed =
          (static_cast<uint64_t>(col) << shift) | seq;
      if (narrow && packed == static_cast<uint64_t>(pad32)) return -2;
      if (!narrow && packed == pad64) return -2;
      int32_t pid = pay_map.get_or_add(span_start, span_len);
      row.emplace_back(packed, pid);
    }
    int64_t nv = r.count();
    for (int64_t e = 0; e < nv && !r.rc; e++) {
      uint64_t rid = r.varint();
      uint64_t val = r.varint();
      bool added;
      int32_t col = rid_map.get_or_add(rid, &added);
      if (col >= n_rep) {
        rc_budget = 1;
        continue;
      }
      if (val >= seq_cap || val > 0xffffffffULL) return -2;
      vv[base_row * n_rep + col] = static_cast<uint32_t>(val);
    }
    int64_t nc = r.count();
    if (nc > c) return -1;
    for (int64_t e = 0; e < nc && !r.rc; e++) {
      uint64_t rid = r.varint();
      uint64_t seq = r.varint();
      bool added;
      int32_t col = rid_map.get_or_add(rid, &added);
      if (col >= n_rep) {
        rc_budget = 1;
        continue;
      }
      if (static_cast<uint64_t>(col) >= col_cap) return -2;
      if (seq >= seq_cap) return -2;
      uint64_t packed = (static_cast<uint64_t>(col) << shift) | seq;
      if (narrow && packed == static_cast<uint64_t>(pad32)) return -2;
      if (!narrow && packed == pad64) return -2;
      crow.push_back(packed);
    }
    if (r.rc) return r.rc;
    if (!r.done()) return -1;
    if (rc_budget) continue;  // still walking for rids_seen, no writes
    // entries: stable sort by packed dot, duplicates keep the LAST wire
    // occurrence (the oracle's dict overwrite)
    std::stable_sort(row.begin(), row.end(),
                     [](const auto& a, const auto& b) {
                       return a.first < b.first;
                     });
    int64_t out = 0;
    for (size_t e = 0; e < row.size(); e++) {
      if (e + 1 < row.size() && row[e + 1].first == row[e].first) continue;
      int64_t at = base_row * w + out;
      if (narrow) {
        dots32[at] = static_cast<int32_t>(row[e].first);
      } else {
        dots64[at] = row[e].first;
      }
      pay[at] = row[e].second;
      out++;
    }
    // cloud: sort + dedup (the oracle's set)
    std::sort(crow.begin(), crow.end());
    crow.erase(std::unique(crow.begin(), crow.end()), crow.end());
    for (size_t e = 0; e < crow.size(); e++) {
      int64_t at = base_row * c + static_cast<int64_t>(e);
      if (narrow) {
        cloud32[at] = static_cast<int32_t>(crow[e]);
      } else {
        cloud64[at] = crow[e];
      }
    }
  }
  *rids_seen_out = static_cast<int64_t>(rid_map.keys.size());
  if (rc_budget) return -3;
  int64_t n_new = static_cast<int64_t>(rid_map.keys.size()) - n_known;
  for (int64_t i = 0; i < n_new; i++) {
    new_rids_out[i] = rid_map.keys[static_cast<size_t>(n_known + i)];
  }
  *n_new_out = n_new;
  *n_pays_out = static_cast<int64_t>(pay_map.offs.size());
  for (size_t i = 0; i < pay_map.offs.size(); i++) {
    pay_span_off[i] = pay_map.offs[i];
    pay_span_len[i] = pay_map.lens[i];
  }
  return 0;
}

}  // extern "C"
