// Native RESP command scanner — the hot per-request parse loop.
//
// The rebuild's [native codec] component for the client API layer
// (SURVEY.md section 2.4: the reference delegates this to pony-resp's
// CommandParser, compiled Pony — a Python loop is not an equivalent).
// Semantics mirror jylis_tpu/server/resp.py exactly; that module stays the
// always-available fallback and this scanner's differential-test oracle.
//
// C ABI, ctypes-friendly: scan ONE command from the head of `buf`.
// Returns:
//   1  command parsed: *consumed = bytes to discard, offs/lens filled with
//      *n_args argument slices (offsets into buf)
//   0  incomplete — feed more bytes
//  -1  protocol error (connection should be dropped)
//  -2  more than max_args arguments: *n_args = required capacity; rescan
//      with bigger arrays
//
// Inline commands (no leading '*') may legally parse to zero args (blank
// line): returns 1 with *n_args = 0; callers skip and continue.

#include <cstdint>
#include <cstring>

namespace {

constexpr int64_t MAX_LINE = 64 * 1024;
constexpr int64_t MAX_BULK = 512LL * 1024 * 1024;  // Redis proto-max-bulk-len
constexpr int64_t MAX_ARRAY = 1024 * 1024;

// find "\r\n" starting at `start`; returns end-of-line index or -1
int64_t find_crlf(const uint8_t* buf, int64_t len, int64_t start) {
    const void* p = memchr(buf + start, '\r', static_cast<size_t>(len - start));
    while (p != nullptr) {
        int64_t i = static_cast<const uint8_t*>(p) - buf;
        if (i + 1 >= len) return -1;
        if (buf[i + 1] == '\n') return i;
        p = memchr(buf + i + 1, '\r', static_cast<size_t>(len - i - 1));
    }
    return -1;
}

// strict non-negative decimal with optional leading '-' (for "$-1"-style
// values the caller range-checks); returns false on empty/garbage
bool parse_int(const uint8_t* s, int64_t n, int64_t* out) {
    if (n <= 0) return false;
    bool neg = false;
    int64_t i = 0;
    if (s[0] == '-') {
        neg = true;
        i = 1;
        if (n == 1) return false;
    }
    int64_t v = 0;
    for (; i < n; i++) {
        if (s[i] < '0' || s[i] > '9') return false;
        if (v > (INT64_MAX - 9) / 10) return false;
        v = v * 10 + (s[i] - '0');
    }
    *out = neg ? -v : v;
    return true;
}

}  // namespace

extern "C" {

int32_t resp_scan(const uint8_t* buf, int64_t len, int64_t* consumed,
                  int64_t* offs, int64_t* lens, int32_t max_args,
                  int32_t* n_args) {
    *consumed = 0;
    *n_args = 0;
    if (len <= 0) return 0;

    if (buf[0] != '*') {
        // inline command: one text line, split on whitespace
        int64_t eol = find_crlf(buf, len, 0);
        if (eol < 0) return len > MAX_LINE ? -1 : 0;
        // separator set matches Python bytes.split(): all ASCII whitespace
        auto is_sep = [](uint8_t c) {
            return c == ' ' || c == '\t' || c == '\n' || c == '\r' ||
                   c == '\v' || c == '\f';
        };
        int32_t count = 0;
        int64_t i = 0;
        while (i < eol) {
            while (i < eol && is_sep(buf[i])) i++;
            if (i >= eol) break;
            int64_t start = i;
            while (i < eol && !is_sep(buf[i])) i++;
            if (count < max_args) {
                offs[count] = start;
                lens[count] = i - start;
            }
            count++;
        }
        if (count > max_args) {
            *n_args = count;
            return -2;
        }
        *n_args = count;
        *consumed = eol + 2;
        return 1;
    }

    // RESP array of bulk strings
    int64_t eol = find_crlf(buf, len, 0);
    if (eol < 0) return len > MAX_LINE ? -1 : 0;
    int64_t n = 0;
    if (!parse_int(buf + 1, eol - 1, &n)) return -1;
    if (n < 0 || n > MAX_ARRAY) return -1;
    if (n > max_args) {
        *n_args = static_cast<int32_t>(n);
        return -2;
    }
    int64_t pos = eol + 2;
    for (int64_t k = 0; k < n; k++) {
        int64_t heol = find_crlf(buf, len, pos);
        if (heol < 0) return len - pos > MAX_LINE ? -1 : 0;
        if (buf[pos] != '$') return -1;
        int64_t blen = 0;
        if (!parse_int(buf + pos + 1, heol - pos - 1, &blen)) return -1;
        if (blen < 0 || blen > MAX_BULK) return -1;
        int64_t body = heol + 2;
        if (body + blen + 2 > len) return 0;
        if (buf[body + blen] != '\r' || buf[body + blen + 1] != '\n') return -1;
        offs[k] = body;
        lens[k] = blen;
        pos = body + blen + 2;
    }
    *n_args = static_cast<int32_t>(n);
    *consumed = pos;
    return 1;
}

// Batch scanner: parse as many complete commands as fit in `buf`,
// amortising the FFI round-trip over a whole pipelined burst.
//
// Outputs: cmd_argc[c] = arg count of command c (an inline blank line
// yields argc -1, meaning "skip"); flat offs/lens hold every argument
// slice in order. Stops at max_cmds commands, max_args total slices, end
// of input, or an incomplete tail.
// Returns: number of parsed commands (>= 0), or -1 on protocol error
// (*consumed then covers the commands parsed BEFORE the error; the
// connection should be dropped after serving them).
int32_t resp_scan_many(const uint8_t* buf, int64_t len, int64_t* consumed,
                       int32_t* cmd_argc, int32_t max_cmds,
                       int64_t* offs, int64_t* lens, int32_t max_args,
                       int32_t* n_args) {
    *consumed = 0;
    *n_args = 0;
    int32_t n_cmds = 0;
    while (n_cmds < max_cmds) {
        int64_t sub_consumed = 0;
        int32_t sub_args = 0;
        int32_t rc =
            resp_scan(buf + *consumed, len - *consumed, &sub_consumed,
                      offs + *n_args, lens + *n_args, max_args - *n_args,
                      &sub_args);
        if (rc == 0) break;  // incomplete tail
        if (rc == -2) {      // caller grows arrays and rescans the tail
            if (n_cmds == 0) {
                *n_args = sub_args;  // required capacity
                return -2;
            }
            break;
        }
        if (rc == -1) return n_cmds ? n_cmds : -1;  // serve prefix first
        bool inline_blank = sub_args == 0 && buf[*consumed] != '*';
        for (int32_t i = 0; i < sub_args; i++) offs[*n_args + i] += *consumed;
        cmd_argc[n_cmds++] = inline_blank ? -1 : sub_args;
        *n_args += sub_args;
        *consumed += sub_consumed;
    }
    return n_cmds;
}

}  // extern "C"
