// Native counter engine — the GCOUNT/PNCOUNT host-state surface.
//
// The reference executes every command inside compiled Pony actors
// (repo_gcount.pony:25-60, repo_pncount.pony:26-67); the rebuild's
// Python engine seam tops out on interpreter dispatch. The counter
// tables (engine.h Table) own the counters' HOST state (key table, own
// contributions, serving value cache, dirty/pending/foreign bookkeeping
// — the exact fields jylis_tpu/models/repo_counters.py otherwise keeps
// in dicts); whole pipelined bursts apply through the all-types batch
// applier in serve_engine.cpp.
//
// Split of responsibilities (single source of truth):
//   * native: per-key own/value/dirty/pending-own/foreign + INC/DEC/GET
//   * Python: device drains, foreign-delta pending (dict of sparse
//     cols), flush/snapshot orchestration, cluster converge — all via
//     the bulk export/apply calls below.
//
// All values are u64 bit patterns; PNCOUNT's serving value is the
// two's-complement wrapped i64 the reference's (p-n).i64() defines.

#include "engine.h"

using namespace jy;

extern "C" {

void* jy_eng_new() { return new Engine(); }
void jy_eng_free(void* e) { delete static_cast<Engine*>(e); }

int64_t jy_eng_rows(void* e, int32_t which) {
    return static_cast<Engine*>(e)->t[which].idx.rows();
}

int64_t jy_eng_upsert(void* e, int32_t which, const uint8_t* k, int64_t n) {
    return static_cast<Engine*>(e)->t[which].upsert(k, n);
}

int64_t jy_eng_find(void* e, int32_t which, const uint8_t* k, int64_t n) {
    return static_cast<Engine*>(e)->t[which].find(k, n);
}

void jy_eng_key(void* e, int32_t which, int64_t row, const uint8_t** ptr,
                int64_t* len) {
    Table& t = static_cast<Engine*>(e)->t[which];
    *ptr = t.idx.key_ptr(row);
    *len = t.idx.key_len[row];
}

void jy_eng_inc(void* e, int32_t which, int64_t row, int32_t polarity,
                uint64_t amount) {
    static_cast<Engine*>(e)->t[which].bump(row, polarity, amount);
}

int32_t jy_eng_is_foreign(void* e, int32_t which, int64_t row) {
    return (static_cast<Engine*>(e)->t[which].flags[row] & F_FOREIGN) ? 1 : 0;
}

void jy_eng_set_foreign(void* e, int32_t which, int64_t row) {
    static_cast<Engine*>(e)->t[which].flags[row] |= F_FOREIGN;
}

uint64_t jy_eng_value(void* e, int32_t which, int64_t row) {
    return static_cast<Engine*>(e)->t[which].value[row];
}

uint64_t jy_eng_own(void* e, int32_t which, int64_t row, int32_t polarity) {
    Table& t = static_cast<Engine*>(e)->t[which];
    return polarity ? t.own_n[row] : t.own_p[row];
}

void jy_eng_own_max(void* e, int32_t which, int64_t row, int32_t polarity,
                    uint64_t v) {
    Table& t = static_cast<Engine*>(e)->t[which];
    uint64_t& own = polarity ? t.own_n[row] : t.own_p[row];
    if (v > own) own = v;
    t.flags[row] |= polarity ? F_OWNSET_N : F_OWNSET_P;
}

int32_t jy_eng_own_set(void* e, int32_t which, int64_t row) {
    uint8_t f = static_cast<Engine*>(e)->t[which].flags[row];
    return ((f & F_OWNSET_P) ? 1 : 0) | ((f & F_OWNSET_N) ? 2 : 0);
}

// drain writeback: authoritative post-join values for these rows; the
// foreign mark clears (the pending batch that made them stale is gone)
void jy_eng_apply_drain(void* e, int32_t which, const int64_t* rows,
                        const uint64_t* values, int64_t n) {
    Table& t = static_cast<Engine*>(e)->t[which];
    for (int64_t i = 0; i < n; i++) {
        t.value[rows[i]] = values[i];
        t.flags[rows[i]] &= static_cast<uint8_t>(~F_FOREIGN);
    }
}

// pending-own export for the drain batch; `clear` zeroes the window
// (callers peek first, drain on device, then clear — so a device failure
// mid-drain leaves the window intact for the retry)
int64_t jy_eng_export_pending(void* e, int32_t which, int64_t* rows,
                              uint64_t* vp, uint64_t* vn, int64_t cap,
                              int32_t clear) {
    Table& t = static_cast<Engine*>(e)->t[which];
    int64_t n = static_cast<int64_t>(t.pend_rows.size());
    if (n > cap) return -n;  // caller regrows buffers
    for (int64_t i = 0; i < n; i++) {
        int64_t r = t.pend_rows[i];
        rows[i] = r;
        vp[i] = (t.flags[r] & F_PEND_P) ? t.pend_p[r] : 0;
        vn[i] = (t.flags[r] & F_PEND_N) ? t.pend_n[r] : 0;
        if (clear) {
            t.flags[r] &= static_cast<uint8_t>(~(F_PEND_P | F_PEND_N));
            t.pend_p[r] = 0;
            t.pend_n[r] = 0;
        }
    }
    if (clear) t.pend_rows.clear();
    return n;
}

// rows changed since the last sync-digest pass (F_SYNCD); clears
int64_t jy_eng_export_sync_dirty(void* e, int32_t which, int64_t* rows,
                                 int64_t cap) {
    Table& t = static_cast<Engine*>(e)->t[which];
    int64_t n = static_cast<int64_t>(t.sync_dirty.size());
    if (n > cap) return -n;
    for (int64_t i = 0; i < n; i++) {
        rows[i] = t.sync_dirty[i];
        t.flags[t.sync_dirty[i]] &= static_cast<uint8_t>(~F_SYNCD);
    }
    t.sync_dirty.clear();
    return n;
}

int64_t jy_eng_dirty_count(void* e, int32_t which) {
    return static_cast<int64_t>(
        static_cast<Engine*>(e)->t[which].dirty_rows.size());
}

int64_t jy_eng_pend_count(void* e, int32_t which) {
    return static_cast<int64_t>(
        static_cast<Engine*>(e)->t[which].pend_rows.size());
}

// flush export: dirty rows + own contributions + own-set bits (bit0 = P
// was written, bit1 = N was written); clears the dirty set
int64_t jy_eng_export_dirty(void* e, int32_t which, int64_t* rows,
                            uint64_t* op, uint64_t* on, uint8_t* set_bits,
                            int64_t cap) {
    Table& t = static_cast<Engine*>(e)->t[which];
    int64_t n = static_cast<int64_t>(t.dirty_rows.size());
    if (n > cap) return -n;
    for (int64_t i = 0; i < n; i++) {
        int64_t r = t.dirty_rows[i];
        rows[i] = r;
        op[i] = t.own_p[r];
        on[i] = t.own_n[r];
        set_bits[i] =
            static_cast<uint8_t>(((t.flags[r] & F_OWNSET_P) ? 1 : 0) |
                                 ((t.flags[r] & F_OWNSET_N) ? 2 : 0));
        t.flags[r] &= static_cast<uint8_t>(~F_DIRTY);
    }
    t.dirty_rows.clear();
    return n;
}

}  // extern "C"
