// Native counter engine — the GCOUNT/PNCOUNT command hot path.
//
// The reference executes every command inside compiled Pony actors
// (repo_gcount.pony:25-60, repo_pncount.pony:26-67); the rebuild's
// Python engine seam tops out on interpreter dispatch. This engine owns
// the counters' HOST state (key table, own contributions, serving value
// cache, dirty/pending/foreign bookkeeping — the exact fields
// jylis_tpu/models/repo_counters.py otherwise keeps in dicts) and
// applies whole pipelined bursts per FFI call: parse (via resp_scan,
// same .so) + table update + reply bytes, all in C++.
//
// Split of responsibilities (single source of truth):
//   * native: per-key own/value/dirty/pending-own/foreign + INC/DEC/GET
//   * Python: device drains, foreign-delta pending (dict of sparse
//     cols), flush/snapshot orchestration, cluster converge — all via
//     the bulk export/apply calls below.
// Any command the engine can't settle exactly like the Python oracle
// (other types, parse errors -> help, GET over a foreign-dirty row,
// variadic weirdness) is returned to Python with its argument slices —
// the caller applies THAT command and re-enters, preserving per-
// connection ordering.
//
// All values are u64 bit patterns; PNCOUNT's serving value is the
// two's-complement wrapped i64 the reference's (p-n).i64() defines.

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" int32_t resp_scan(const uint8_t* buf, int64_t len,
                             int64_t* consumed, int64_t* offs, int64_t* lens,
                             int32_t max_args, int32_t* n_args);

namespace {

constexpr uint8_t F_FOREIGN = 1;
constexpr uint8_t F_DIRTY = 2;
constexpr uint8_t F_PEND_P = 4;
constexpr uint8_t F_PEND_N = 8;
// "own was ever written" per polarity: flush emits a polarity's entry
// only when set, matching the Python dicts' key-presence semantics
// (an INC of 0 still creates the entry)
constexpr uint8_t F_OWNSET_P = 16;
constexpr uint8_t F_OWNSET_N = 32;

struct Table {
    // open-addressing key table (FNV-1a, power-of-two, linear probe)
    std::vector<int64_t> slot_row;  // -1 empty
    std::vector<uint8_t> arena;     // key bytes, append-only
    std::vector<int64_t> key_off;
    std::vector<int64_t> key_len;
    std::vector<uint64_t> key_hash;
    // per-row state
    std::vector<uint64_t> value;  // serving value (u64 bits)
    std::vector<uint64_t> own_p;
    std::vector<uint64_t> own_n;
    std::vector<uint64_t> pend_p;  // max own within the drain window
    std::vector<uint64_t> pend_n;
    std::vector<uint8_t> flags;
    std::vector<int64_t> dirty_rows;  // insertion order; F_DIRTY dedups
    std::vector<int64_t> pend_rows;   // rows with any F_PEND_*

    Table() : slot_row(64, -1) {}

    size_t mask() const { return slot_row.size() - 1; }

    static uint64_t hash(const uint8_t* k, int64_t n) {
        uint64_t h = 1469598103934665603ull;
        for (int64_t i = 0; i < n; i++) h = (h ^ k[i]) * 1099511628211ull;
        return h;
    }

    bool key_eq(int64_t row, const uint8_t* k, int64_t n) const {
        return key_len[row] == n &&
               memcmp(arena.data() + key_off[row], k, static_cast<size_t>(n)) == 0;
    }

    void rehash() {
        std::vector<int64_t> fresh(slot_row.size() * 2, -1);
        size_t m = fresh.size() - 1;
        for (size_t r = 0; r < key_off.size(); r++) {
            size_t i = key_hash[r] & m;
            while (fresh[i] >= 0) i = (i + 1) & m;
            fresh[i] = static_cast<int64_t>(r);
        }
        slot_row.swap(fresh);
    }

    int64_t find(const uint8_t* k, int64_t n) const {
        uint64_t h = hash(k, n);
        size_t i = h & mask();
        while (true) {
            int64_t row = slot_row[i];
            if (row < 0) return -1;
            if (key_hash[row] == h && key_eq(row, k, n)) return row;
            i = (i + 1) & mask();
        }
    }

    int64_t upsert(const uint8_t* k, int64_t n) {
        uint64_t h = hash(k, n);
        size_t i = h & mask();
        while (true) {
            int64_t row = slot_row[i];
            if (row < 0) break;
            if (key_hash[row] == h && key_eq(row, k, n)) return row;
            i = (i + 1) & mask();
        }
        int64_t row = static_cast<int64_t>(key_off.size());
        key_off.push_back(static_cast<int64_t>(arena.size()));
        key_len.push_back(n);
        key_hash.push_back(h);
        arena.insert(arena.end(), k, k + n);
        value.push_back(0);
        own_p.push_back(0);
        own_n.push_back(0);
        pend_p.push_back(0);
        pend_n.push_back(0);
        flags.push_back(0);
        slot_row[i] = row;
        if (key_off.size() * 10 >= slot_row.size() * 7) rehash();
        return row;
    }

    void mark_dirty(int64_t row) {
        if (!(flags[row] & F_DIRTY)) {
            flags[row] |= F_DIRTY;
            dirty_rows.push_back(row);
        }
    }

    // INC (polarity 0) / DEC (polarity 1): the exact sequence of
    // repo_counters.py _inc / PN apply
    void bump(int64_t row, int polarity, uint64_t amount) {
        uint64_t& own = polarity ? own_n[row] : own_p[row];
        uint64_t& pend = polarity ? pend_n[row] : pend_p[row];
        uint8_t bit = polarity ? F_PEND_N : F_PEND_P;
        flags[row] |= polarity ? F_OWNSET_N : F_OWNSET_P;
        own += amount;  // u64 wrap
        if (own > pend) pend = own;
        if (!(flags[row] & (F_PEND_P | F_PEND_N))) pend_rows.push_back(row);
        flags[row] |= bit;
        mark_dirty(row);
        value[row] += polarity ? static_cast<uint64_t>(-amount) : amount;
    }
};

struct Engine {
    Table t[2];  // 0 = GCOUNT, 1 = PNCOUNT
};

// ---- reply formatting ------------------------------------------------------

int64_t fmt_u64(uint8_t* out, uint64_t v) {
    char tmp[24];
    int n = 0;
    do {
        tmp[n++] = static_cast<char>('0' + v % 10);
        v /= 10;
    } while (v);
    for (int i = 0; i < n; i++) out[i] = static_cast<uint8_t>(tmp[n - 1 - i]);
    return n;
}

int64_t fmt_int_reply(uint8_t* out, uint64_t bits, bool signed_i64) {
    int64_t n = 0;
    out[n++] = ':';
    if (signed_i64 && static_cast<int64_t>(bits) < 0) {
        out[n++] = '-';
        bits = ~bits + 1;  // unsigned-domain negate: defined for INT64_MIN
    }
    n += fmt_u64(out + n, bits);
    out[n++] = '\r';
    out[n++] = '\n';
    return n;
}

// strict u64 parse: ASCII digits only, must fit (Python parse_u64)
bool parse_amount(const uint8_t* s, int64_t n, uint64_t* out) {
    if (n <= 0) return false;
    uint64_t v = 0;
    for (int64_t i = 0; i < n; i++) {
        if (s[i] < '0' || s[i] > '9') return false;
        uint64_t d = static_cast<uint64_t>(s[i] - '0');
        if (v > (UINT64_MAX - d) / 10) return false;
        v = v * 10 + d;
    }
    *out = v;
    return true;
}

bool word_is(const uint8_t* buf, int64_t off, int64_t len, const char* w) {
    int64_t n = static_cast<int64_t>(strlen(w));
    return len == n && memcmp(buf + off, w, static_cast<size_t>(n)) == 0;
}

}  // namespace

extern "C" {

void* jy_eng_new() { return new Engine(); }
void jy_eng_free(void* e) { delete static_cast<Engine*>(e); }

int64_t jy_eng_rows(void* e, int32_t which) {
    return static_cast<int64_t>(
        static_cast<Engine*>(e)->t[which].key_off.size());
}

int64_t jy_eng_upsert(void* e, int32_t which, const uint8_t* k, int64_t n) {
    return static_cast<Engine*>(e)->t[which].upsert(k, n);
}

int64_t jy_eng_find(void* e, int32_t which, const uint8_t* k, int64_t n) {
    return static_cast<Engine*>(e)->t[which].find(k, n);
}

void jy_eng_key(void* e, int32_t which, int64_t row, const uint8_t** ptr,
                int64_t* len) {
    Table& t = static_cast<Engine*>(e)->t[which];
    *ptr = t.arena.data() + t.key_off[row];
    *len = t.key_len[row];
}

void jy_eng_inc(void* e, int32_t which, int64_t row, int32_t polarity,
                uint64_t amount) {
    static_cast<Engine*>(e)->t[which].bump(row, polarity, amount);
}

int32_t jy_eng_is_foreign(void* e, int32_t which, int64_t row) {
    return (static_cast<Engine*>(e)->t[which].flags[row] & F_FOREIGN) ? 1 : 0;
}

void jy_eng_set_foreign(void* e, int32_t which, int64_t row) {
    static_cast<Engine*>(e)->t[which].flags[row] |= F_FOREIGN;
}

uint64_t jy_eng_value(void* e, int32_t which, int64_t row) {
    return static_cast<Engine*>(e)->t[which].value[row];
}

uint64_t jy_eng_own(void* e, int32_t which, int64_t row, int32_t polarity) {
    Table& t = static_cast<Engine*>(e)->t[which];
    return polarity ? t.own_n[row] : t.own_p[row];
}

void jy_eng_own_max(void* e, int32_t which, int64_t row, int32_t polarity,
                    uint64_t v) {
    Table& t = static_cast<Engine*>(e)->t[which];
    uint64_t& own = polarity ? t.own_n[row] : t.own_p[row];
    if (v > own) own = v;
    t.flags[row] |= polarity ? F_OWNSET_N : F_OWNSET_P;
}

int32_t jy_eng_own_set(void* e, int32_t which, int64_t row) {
    uint8_t f = static_cast<Engine*>(e)->t[which].flags[row];
    return ((f & F_OWNSET_P) ? 1 : 0) | ((f & F_OWNSET_N) ? 2 : 0);
}

// drain writeback: authoritative post-join values for these rows; the
// foreign mark clears (the pending batch that made them stale is gone)
void jy_eng_apply_drain(void* e, int32_t which, const int64_t* rows,
                        const uint64_t* values, int64_t n) {
    Table& t = static_cast<Engine*>(e)->t[which];
    for (int64_t i = 0; i < n; i++) {
        t.value[rows[i]] = values[i];
        t.flags[rows[i]] &= static_cast<uint8_t>(~F_FOREIGN);
    }
}

// pending-own export for the drain batch; `clear` zeroes the window
// (callers peek first, drain on device, then clear — so a device failure
// mid-drain leaves the window intact for the retry)
int64_t jy_eng_export_pending(void* e, int32_t which, int64_t* rows,
                              uint64_t* vp, uint64_t* vn, int64_t cap,
                              int32_t clear) {
    Table& t = static_cast<Engine*>(e)->t[which];
    int64_t n = static_cast<int64_t>(t.pend_rows.size());
    if (n > cap) return -n;  // caller regrows buffers
    for (int64_t i = 0; i < n; i++) {
        int64_t r = t.pend_rows[i];
        rows[i] = r;
        vp[i] = (t.flags[r] & F_PEND_P) ? t.pend_p[r] : 0;
        vn[i] = (t.flags[r] & F_PEND_N) ? t.pend_n[r] : 0;
        if (clear) {
            t.flags[r] &= static_cast<uint8_t>(~(F_PEND_P | F_PEND_N));
            t.pend_p[r] = 0;
            t.pend_n[r] = 0;
        }
    }
    if (clear) t.pend_rows.clear();
    return n;
}

int64_t jy_eng_dirty_count(void* e, int32_t which) {
    return static_cast<int64_t>(
        static_cast<Engine*>(e)->t[which].dirty_rows.size());
}

int64_t jy_eng_pend_count(void* e, int32_t which) {
    return static_cast<int64_t>(
        static_cast<Engine*>(e)->t[which].pend_rows.size());
}

// flush export: dirty rows + own contributions + own-set bits (bit0 = P
// was written, bit1 = N was written); clears the dirty set
int64_t jy_eng_export_dirty(void* e, int32_t which, int64_t* rows,
                            uint64_t* op, uint64_t* on, uint8_t* set_bits,
                            int64_t cap) {
    Table& t = static_cast<Engine*>(e)->t[which];
    int64_t n = static_cast<int64_t>(t.dirty_rows.size());
    if (n > cap) return -n;
    for (int64_t i = 0; i < n; i++) {
        int64_t r = t.dirty_rows[i];
        rows[i] = r;
        op[i] = t.own_p[r];
        on[i] = t.own_n[r];
        set_bits[i] =
            static_cast<uint8_t>(((t.flags[r] & F_OWNSET_P) ? 1 : 0) |
                                 ((t.flags[r] & F_OWNSET_N) ? 2 : 0));
        t.flags[r] &= static_cast<uint8_t>(~F_DIRTY);
    }
    t.dirty_rows.clear();
    return n;
}

// ---- the batch applier -----------------------------------------------------
//
// Returns:
//   0  consumed all complete commands (tail incomplete or buffer empty)
//   1  stopped at a command Python must apply: its slices are in
//      offs/lens/n_args and *consumed INCLUDES it
//   2  reply buffer nearly full: flush replies and call again
//  -1  protocol error at the stop point (serve replies, drop connection)
//  -2  a command has more than max_args arguments (grow and retry)
int32_t jy_eng_scan_apply(void* ev, const uint8_t* buf, int64_t len,
                          uint8_t* out, int64_t out_cap, int64_t* out_len,
                          int64_t* consumed, int64_t* offs, int64_t* lens,
                          int32_t max_args, int32_t* n_args,
                          int32_t* changed_g, int32_t* changed_pn) {
    Engine* eng = static_cast<Engine*>(ev);
    *out_len = 0;
    *consumed = 0;
    *n_args = 0;
    *changed_g = 0;
    *changed_pn = 0;
    while (true) {
        if (out_cap - *out_len < 32) return 2;
        int64_t sub_consumed = 0;
        int32_t argc = 0;
        int32_t rc = resp_scan(buf + *consumed, len - *consumed, &sub_consumed,
                               offs, lens, max_args, &argc);
        if (rc == 0) return 0;
        if (rc == -1) return -1;
        if (rc == -2) {
            *n_args = argc;
            return -2;
        }
        for (int32_t i = 0; i < argc; i++) offs[i] += *consumed;
        bool inline_blank = argc == 0 && buf[*consumed] != '*';
        if (inline_blank) {  // oracle parser skips blank inline lines
            *consumed += sub_consumed;
            continue;
        }
        // which table?
        int32_t which = -1;
        if (argc >= 1 && word_is(buf, offs[0], lens[0], "GCOUNT")) which = 0;
        if (argc >= 1 && word_is(buf, offs[0], lens[0], "PNCOUNT")) which = 1;
        if (which < 0) {
            *n_args = argc;
            *consumed += sub_consumed;
            return 1;
        }
        Table& t = eng->t[which];
        int32_t* changed = which ? changed_pn : changed_g;
        // GET key — reply from the value cache unless foreign-dirty
        if (argc >= 3 && word_is(buf, offs[1], lens[1], "GET")) {
            int64_t row = t.find(buf + offs[2], lens[2]);
            if (row >= 0 && (t.flags[row] & F_FOREIGN)) {
                *n_args = argc;  // Python drains and serves this one
                *consumed += sub_consumed;
                return 1;
            }
            uint64_t v = row >= 0 ? t.value[row] : 0;
            *out_len += fmt_int_reply(out + *out_len, v, which == 1);
            *consumed += sub_consumed;
            continue;
        }
        // INC/DEC key amount
        int polarity = -1;
        if (argc >= 4 && word_is(buf, offs[1], lens[1], "INC")) polarity = 0;
        if (which == 1 && argc >= 4 && word_is(buf, offs[1], lens[1], "DEC"))
            polarity = 1;
        if (polarity >= 0) {
            uint64_t amount = 0;
            if (!parse_amount(buf + offs[3], lens[3], &amount)) {
                *n_args = argc;  // ParseError -> help text, Python's job
                *consumed += sub_consumed;
                return 1;
            }
            int64_t row = t.upsert(buf + offs[2], lens[2]);
            t.bump(row, polarity, amount);
            (*changed)++;
            memcpy(out + *out_len, "+OK\r\n", 5);
            *out_len += 5;
            *consumed += sub_consumed;
            continue;
        }
        // unknown subcommand / wrong arity -> help path in Python
        *n_args = argc;
        *consumed += sub_consumed;
        return 1;
    }
}

}  // extern "C"
