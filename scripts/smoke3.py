#!/usr/bin/env python
"""Three-node cluster smoke test (reference analog: the upstream's
TestCluster three-node convergence assertion, test_cluster.pony:67-130,
run against REAL server processes).

Two modes:

  --ports 6379,6380,6381   drive three already-running nodes (e.g. the
                           docker-compose cluster) over RESP
  --spawn                  spawn three local node processes first (no
                           container runtime needed — what CI uses here)

Each node INCs the same GCOUNT key with a different amount (2, 3, 4 — the
reference test's exact workload), every node must converge to 9; then one
write per remaining type (PNCOUNT/TREG/TLOG/UJSON) lands on a different
node and must read back converged everywhere.
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SPAWN = (
    "from jylis_tpu.utils.vcpu import force_virtual_cpu; force_virtual_cpu(8); "
    "import sys; from jylis_tpu.main import main; main(sys.argv[1:])"
)


def resp(*args) -> bytes:
    out = b"*%d\r\n" % len(args)
    for a in args:
        if isinstance(a, str):
            a = a.encode()
        out += b"$%d\r\n%s\r\n" % (len(a), a)
    return out


class _Conn:
    """Buffered RESP connection: parses exactly one complete reply per
    command so a reply split across TCP segments can never desync the
    stream (endswith-style heuristics truncate multi-frame arrays)."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.buf = b""

    def _fill(self) -> None:
        chunk = self.sock.recv(65536)
        if not chunk:
            raise RuntimeError("connection closed")
        self.buf += chunk

    def _line(self) -> bytes:
        while b"\r\n" not in self.buf:
            self._fill()
        line, self.buf = self.buf.split(b"\r\n", 1)
        return line

    def _reply(self) -> bytes:
        """Consume one reply from the stream, returning its exact bytes."""
        line = self._line()
        out = line + b"\r\n"
        kind = line[:1]
        if kind in (b"+", b"-", b":"):
            return out
        if kind == b"$":
            n = int(line[1:])
            if n < 0:
                return out  # null bulk string
            while len(self.buf) < n + 2:
                self._fill()
            out += self.buf[: n + 2]
            self.buf = self.buf[n + 2 :]
            return out
        if kind == b"*":
            for _ in range(max(int(line[1:]), 0)):
                out += self._reply()
            return out
        raise RuntimeError(f"unparseable reply line: {line!r}")


def cmd(conn: _Conn, *args) -> bytes:
    conn.sock.sendall(resp(*args))
    conn.sock.settimeout(30)
    return conn._reply()


def until(deadline: float, fn, what: str) -> None:
    while time.time() < deadline:
        try:
            if fn():
                return
        except Exception:
            pass
        time.sleep(0.25)
    raise SystemExit(f"SMOKE FAILED: timed out waiting for {what}")


def connect_all(ports, deadline) -> list[_Conn]:
    conns = []
    for p in ports:
        while True:
            try:
                conns.append(
                    _Conn(socket.create_connection(("127.0.0.1", p), timeout=2))
                )
                break
            except OSError:
                if time.time() > deadline:
                    raise SystemExit(f"SMOKE FAILED: node on :{p} never came up")
                time.sleep(0.5)
    return conns


def run_smoke(ports) -> None:
    deadline = time.time() + 120
    conns = connect_all(ports, deadline)

    # the reference test's exact convergence assertion: 2 + 3 + 4 == 9
    for c, amount in zip(conns, ("2", "3", "4")):
        assert cmd(c, "GCOUNT", "INC", "smoke", amount) == b"+OK\r\n"
    for i, c in enumerate(conns):
        until(
            deadline,
            lambda c=c: cmd(c, "GCOUNT", "GET", "smoke") == b":9\r\n",
            f"GCOUNT convergence at node {i}",
        )

    # one write per remaining type, each landing on a different node
    assert cmd(conns[0], "PNCOUNT", "INC", "pn", "10") == b"+OK\r\n"
    assert cmd(conns[1], "PNCOUNT", "DEC", "pn", "3") == b"+OK\r\n"
    assert cmd(conns[1], "TREG", "SET", "reg", "hello", "42") == b"+OK\r\n"
    assert cmd(conns[2], "TLOG", "INS", "log", "entry", "7") == b"+OK\r\n"
    assert cmd(conns[0], "UJSON", "SET", "doc", "k", '"v"') == b"+OK\r\n"
    for i, c in enumerate(conns):
        until(deadline, lambda c=c: cmd(c, "PNCOUNT", "GET", "pn") == b":7\r\n",
              f"PNCOUNT at node {i}")
        until(deadline, lambda c=c: cmd(c, "TREG", "GET", "reg")
              == b"*2\r\n$5\r\nhello\r\n:42\r\n", f"TREG at node {i}")
        until(deadline, lambda c=c: cmd(c, "TLOG", "GET", "log")
              == b"*1\r\n*2\r\n$5\r\nentry\r\n:7\r\n", f"TLOG at node {i}")
        until(deadline, lambda c=c: cmd(c, "UJSON", "GET", "doc")
              == b'$9\r\n{"k":"v"}\r\n', f"UJSON at node {i}")
    print("SMOKE3-OK")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ports", default=None,
                    help="comma-separated RESP ports of running nodes")
    ap.add_argument("--spawn", action="store_true",
                    help="spawn three local node processes (no containers)")
    args = ap.parse_args()

    if args.spawn:
        ports = [7411, 7412, 7413]
        cports = [17411, 17412, 17413]
        names = ["smoke-a", "smoke-b", "smoke-c"]
        seed = f"127.0.0.1:{cports[0]}:{names[0]}"
        procs = []
        try:
            for i, (p, cp, name) in enumerate(zip(ports, cports, names)):
                argv = [sys.executable, "-c", SPAWN, "--port", str(p),
                        "--addr", f"127.0.0.1:{cp}:{name}",
                        "--heartbeat-time", "0.2", "--log-level", "warn"]
                if i > 0:
                    argv += ["--seed-addrs", seed]
                procs.append(subprocess.Popen(argv, cwd=REPO))
            run_smoke(ports)
        finally:
            for pr in procs:
                pr.terminate()
            for pr in procs:
                pr.wait(timeout=30)
    elif args.ports:
        run_smoke([int(p) for p in args.ports.split(",")])
    else:
        ap.error("need --ports or --spawn")


if __name__ == "__main__":
    main()
