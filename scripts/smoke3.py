#!/usr/bin/env python
"""Three-node cluster smoke test (reference analog: the upstream's
TestCluster three-node convergence assertion, test_cluster.pony:67-130,
run against REAL server processes).

Two modes:

  --ports 6379,6380,6381   drive three already-running nodes (e.g. the
                           docker-compose cluster) over RESP
  --spawn                  spawn three local node processes first (no
                           container runtime needed — what CI uses here)

Each node INCs the same GCOUNT key with a different amount (2, 3, 4 — the
reference test's exact workload), every node must converge to 9; then one
write per remaining type (PNCOUNT/TREG/TLOG/UJSON/TENSOR) lands on a
different node and must read back converged everywhere — TENSOR writes
the same key from two nodes (element-wise MAX over a binary f32 payload)
and additionally gates on SYSTEM DIGEST equality across all three.

Every poll opens a fresh connection through jylis_tpu.client (the in-repo
RESP client): a reply stalled past its timeout can therefore never desync
a long-lived stream into spurious failures, and a crashed node surfaces
as its connect error, not a silent stall.
"""

from __future__ import annotations

import argparse
import os
import struct
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from jylis_tpu.client import Client, ResponseError  # noqa: E402

SPAWN = (
    "from jylis_tpu.utils.vcpu import force_virtual_cpu; force_virtual_cpu(8); "
    "import sys; from jylis_tpu.main import main; main(sys.argv[1:])"
)


def once(port: int, *args):
    """One command on a fresh connection; returns the decoded reply."""
    with Client("127.0.0.1", port, timeout=30) as c:
        return c.execute_command(*args)


def until(deadline: float, fn, what: str) -> None:
    last_err = None
    while time.time() < deadline:
        try:
            if fn():
                return
        except (OSError, RuntimeError, ResponseError, AssertionError) as e:
            # exactly the transient classes a still-booting or busy node
            # produces (connect refused/reset, mid-handshake close,
            # SHUTDOWN-error replies, not-yet-converged assertions) —
            # anything else is a bug in the smoke itself and must raise,
            # not spin until the deadline
            last_err = e
        time.sleep(0.25)
    detail = f" (last error: {last_err!r})" if last_err else ""
    raise SystemExit(f"SMOKE FAILED: timed out waiting for {what}{detail}")


def wait_up(ports, deadline) -> None:
    for p in ports:
        until(deadline, lambda p=p: once(p, "GCOUNT", "GET", "up-probe") == 0,
              f"node on :{p} to come up")


def run_smoke(ports, addrs=None) -> None:
    """``addrs``: optional [(host, cluster_port, name)] triples — known
    in --spawn mode, where they unlock the cross-node escrow-transfer
    leg (replica ids derive from advertised addresses)."""
    deadline = time.time() + 180
    wait_up(ports, deadline)

    # the reference test's exact convergence assertion: 2 + 3 + 4 == 9
    for p, amount in zip(ports, (2, 3, 4)):
        assert once(p, "GCOUNT", "INC", "smoke", amount) == b"OK"
    for p in ports:
        until(deadline, lambda p=p: once(p, "GCOUNT", "GET", "smoke") == 9,
              f"GCOUNT convergence on :{p}")

    # one write per remaining type, each landing on a different node
    assert once(ports[0], "PNCOUNT", "INC", "pn", 10) == b"OK"
    assert once(ports[1], "PNCOUNT", "DEC", "pn", 3) == b"OK"
    assert once(ports[1], "TREG", "SET", "reg", "hello", 42) == b"OK"
    assert once(ports[2], "TLOG", "INS", "log", "entry", 7) == b"OK"
    assert once(ports[0], "UJSON", "SET", "doc", "k", '"v"') == b"OK"
    # composed types (schema v9): MAP fields written on different nodes
    # (decomposed per-field deltas converge them), one removed; BCOUNT
    # escrow granted on node 0, transferred to node 2's replica, spent
    # there — the bounded write requires the transfer to have converged
    assert once(ports[0], "MAP", "TREG", "SET", "m", "fa", "va", 5) == b"OK"
    assert once(ports[1], "MAP", "GCOUNT", "SET", "m", "fb", 4) == b"OK"
    assert once(ports[2], "MAP", "TREG", "SET", "m", "dead", "x", 1) == b"OK"
    assert once(ports[2], "MAP", "TREG", "DEL", "m", "dead") == b"OK"
    assert once(ports[0], "BCOUNT", "GRANT", "inv", 9) == b"OK"
    assert once(ports[0], "BCOUNT", "INC", "inv", 9) == b"OK"
    # TENSOR: two nodes write the same key; element-wise MAX must settle
    # both payloads' coordinate-wise maximum everywhere (binary-safe
    # bulk payloads over real sockets)
    assert once(
        ports[1], "TENSOR", "SET", "emb", "MAX", 0,
        struct.pack("<2f", 1.0, 9.0),
    ) == b"OK"
    assert once(
        ports[2], "TENSOR", "SET", "emb", "MAX", 0,
        struct.pack("<2f", 5.0, 2.0),
    ) == b"OK"
    tensor_want = [b"MAX", struct.pack("<2f", 5.0, 9.0), 0]
    for p in ports:
        until(deadline, lambda p=p: once(p, "PNCOUNT", "GET", "pn") == 7,
              f"PNCOUNT on :{p}")
        until(deadline, lambda p=p: once(p, "TREG", "GET", "reg")
              == [b"hello", 42], f"TREG on :{p}")
        until(deadline, lambda p=p: once(p, "TLOG", "GET", "log")
              == [[b"entry", 7]], f"TLOG on :{p}")
        until(deadline, lambda p=p: once(p, "UJSON", "GET", "doc")
              == b'{"k":"v"}', f"UJSON on :{p}")
        until(deadline, lambda p=p: once(p, "TENSOR", "GET", "emb")
              == tensor_want, f"TENSOR on :{p}")
        until(deadline, lambda p=p: once(p, "MAP", "TREG", "GET", "m", "fa")
              == [b"va", 5], f"MAP TREG field on :{p}")
        until(deadline, lambda p=p: once(p, "MAP", "GCOUNT", "GET", "m", "fb")
              == 4, f"MAP GCOUNT field on :{p}")
        until(deadline,
              lambda p=p: once(p, "MAP", "TREG", "GET", "m", "dead") is None,
              f"MAP tombstone on :{p}")
        until(deadline, lambda p=p: once(p, "BCOUNT", "GET", "inv")
              == [9, 9], f"BCOUNT converged view on :{p}")
    # escrow mobility across REAL nodes (spawn mode, where the cluster
    # addresses — and so the advertised-address-derived replica ids —
    # are known): node 0's replica hands dec-escrow to node 2's; the
    # spend can only succeed after the transfer delta converges onto
    # node 2, so the until() loop IS the end-to-end proof
    want_value = 9
    if addrs is not None:
        from jylis_tpu.utils.address import Address

        rid2 = Address(*addrs[2]).hash64()
        assert once(ports[0], "BCOUNT", "TRANSFER", "inv", rid2, 3) == b"OK"
        until(
            deadline,
            lambda: once(ports[2], "BCOUNT", "DEC", "inv", 3) == b"OK",
            "transferred escrow to fund node 2's decrement",
        )
        want_value = 6
    for p in ports:
        until(deadline, lambda p=p: once(p, "BCOUNT", "GET", "inv")
              == [want_value, 9], f"BCOUNT post-spend on :{p}")

    # the acceptance gate, upgraded to the per-type breakdown (SYSTEM
    # DIGEST TYPES): all three nodes must agree on EVERY type's digest
    # line — a divergence is localized to its type in the failure
    # output instead of one opaque combined hash. The type list is read
    # from the NODES (the registry's own enumeration), never hardcoded
    # here: a future type cannot silently fall out of this gate.
    def digest_types_match() -> bool:
        rows = [once(p, "SYSTEM", "DIGEST", "TYPES") for p in ports]
        types_seen = [bytes(line).split()[0] for line in rows[0]]
        for required in (b"MAP", b"BCOUNT", b"GCOUNT", b"TENSOR"):
            assert required in types_seen, (required, types_seen)
        assert all(len(r) == len(rows[0]) for r in rows), rows
        mismatched = [
            tuple(bytes(line).split()[0] for line in r if line not in rows[0])
            for r in rows[1:]
        ]
        assert all(not m for m in mismatched), (
            f"per-type digest mismatch (diverged types: {mismatched})"
        )
        return True

    until(deadline, digest_types_match,
          "SYSTEM DIGEST TYPES match across all three nodes")
    # the combined digest must agree with the per-type agreement
    until(
        deadline,
        lambda: len({bytes(once(p, "SYSTEM", "DIGEST")) for p in ports}) == 1,
        "SYSTEM DIGEST match across all three nodes",
    )

    # session-guarantee gate (docs/sessions.md): a write WRAPped on
    # node 0 mints a token; SESSION READ with that token on the OTHER
    # nodes must serve the write (bounded wait riding --session-wait-ms;
    # transient STALE replies poll like any convergence) and return a
    # monotone reply token. Read-your-writes across real processes and
    # real sockets, end to end.
    reply = once(ports[0], "SESSION", "WRAP", "GCOUNT", "INC", "sess", 5)
    assert isinstance(reply, list) and len(reply) == 2, reply
    assert reply[0] == b"OK", reply
    token = bytes(reply[1])

    def session_read_ok(p: int) -> bool:
        try:
            out = once(p, "SESSION", "READ", token, "GCOUNT", "GET", "sess")
        except ResponseError as e:
            assert str(e).startswith("STALE"), e  # the only legal refusal
            return False
        assert isinstance(out, list) and len(out) == 2, out
        assert out[1] == 5, out
        from jylis_tpu import sessions as _sessions

        vec = _sessions.decode_token(bytes(out[0]))
        assert _sessions.dominates(vec, _sessions.decode_token(token))
        return True

    for p in ports[1:]:
        until(deadline, lambda p=p: session_read_ok(p),
              f"session read-your-writes on :{p}")
    print("SMOKE3-OK")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ports", default=None,
                    help="comma-separated RESP ports of running nodes")
    ap.add_argument("--spawn", action="store_true",
                    help="spawn three local node processes (no containers)")
    args = ap.parse_args()

    if args.spawn:
        ports = [7411, 7412, 7413]
        cports = [17411, 17412, 17413]
        names = ["smoke-a", "smoke-b", "smoke-c"]
        seed = f"127.0.0.1:{cports[0]}:{names[0]}"
        procs = []
        try:
            for i, (p, cp, name) in enumerate(zip(ports, cports, names)):
                argv = [sys.executable, "-c", SPAWN, "--port", str(p),
                        "--addr", f"127.0.0.1:{cp}:{name}",
                        "--heartbeat-time", "0.2", "--log-level", "warn"]
                if i > 0:
                    argv += ["--seed-addrs", seed]
                procs.append(subprocess.Popen(argv, cwd=REPO))
            run_smoke(
                ports,
                addrs=[
                    ("127.0.0.1", str(cp), name)
                    for cp, name in zip(cports, names)
                ],
            )
        finally:
            # terminate EVERY node even if one outlives its grace period:
            # a wedged first node must not leak the others (they hold the
            # fixed smoke ports)
            for pr in procs:
                pr.terminate()
            for pr in procs:
                try:
                    pr.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pr.kill()
                    pr.wait(timeout=10)
    elif args.ports:
        run_smoke([int(p) for p in args.ports.split(",")])
    else:
        ap.error("need --ports or --spawn")


if __name__ == "__main__":
    main()
