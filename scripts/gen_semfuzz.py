"""Manifest-generated differential RESP fuzzer (jlint pass 11).

``scripts/jlint/semantics_manifest.json`` records the argument grammar
of every natively-served command. This module turns that grammar into
*executable* coverage, the way pass 8 turns the lattice manifest into
``tests/test_lattice_laws.py``:

* ``gen_streams`` builds deterministic command streams from the
  grammar — valid-by-grammar commands, boundary tokens (u64 extremes,
  leading zeros, empty and binary keys, oversized values), and mutated
  invalid commands (arity off by one, corrupted subcommand case,
  non-digit amounts, invalid UTF-8 path components, broken JSON) —
  seeded by ``random.Random`` only, so a (seed, grammar) pair always
  produces the same bytes;
* ``render_harness`` emits ``tests/test_semantic_fuzz.py`` (regenerated
  by ``python -m scripts.jlint --write-manifest``; staleness is JL1103)
  which drives every stream through the full Server twice — native
  engine vs forced-Python oracle — and byte-compares the replies;
* ``write_corpus`` records ``tests/golden/semfuzz_corpus.json``: the
  generation seed, each stream's sha256, and the sha256 of the manifest
  itself — so editing the manifest without re-recording
  (``--write-corpus``) fails in tier-1, golden-corpus-style.

The differential needs no expected-reply model: an invalid command is
help text on BOTH paths (the engine defers every error to the oracle),
so byte-equality is the whole assertion.
"""

from __future__ import annotations

import hashlib
import json
import os
import random

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS_PATH = os.path.join(ROOT, "tests", "golden", "semfuzz_corpus.json")

# deterministic token pools (binary-safety rides on the RESP array
# framing: keys and values may contain \r\n, NUL, and invalid UTF-8)
KEYS = [b"k", b"key2", b"", b"a b", b"caf\xc3\xa9", b"\x00\xff\r\n", b"x" * 300]
U64_VALID = [b"0", b"1", b"7", b"007", b"1000000007", b"18446744073709551615"]
U64_INVALID = [b"", b"-1", b"+2", b"9" * 25, b"1x", b"0x10", b" 1", b"zz"]
PRIMS = [b"1", b"-2.5", b"true", b"false", b"null", b'"s"', b'"caf\xc3\xa9"']
DOCS = PRIMS + [b'{"a":1}', b"[1,2,3]", b'{"a":{"b":[1]}}']
BAD_JSON = [b"{", b"nope", b"'x'", b"\xff", b"1 2"]
PATH_PARTS = [b"a", b"tags", b"meta", b"caf\xc3\xa9", b"deep"]
BAD_PATH = b"\xff\xfe"  # invalid UTF-8: native defers, oracle decodes


def _is_path_command(g: dict) -> bool:
    return any(v.get("arg") == "path" for v in g.get("validators", [])) or g.get(
        "kind"
    ) == "path"


def _value_pool(g: dict) -> list[bytes] | None:
    for v in g.get("validators", []):
        if v.get("check") == "ujson_doc_ok":
            return DOCS
        if v.get("check") == "ujson_prim_ok":
            return PRIMS
    return None


def _gen_args(rng: random.Random, key: str, g: dict) -> list[bytes]:
    """One client command (list of RESP array args) for grammar entry
    ``g`` — mostly valid, sometimes boundary, sometimes mutated."""
    tword, sub = key.split(" ")
    roll = rng.random()
    min_argc = g["min_argc"]
    u64_at = set(g["u64_args"])
    opt_at = set(g["opt_u64_args"])
    values = _value_pool(g)
    pathy = tword == "UJSON"
    argc = min_argc
    if opt_at and rng.random() < 0.5:
        argc = max(argc, max(opt_at) + 1)
    if pathy and rng.random() < 0.6:
        argc += rng.randrange(1, 3)  # deeper paths stay valid-by-grammar
    args = [tword.encode(), sub.encode()]
    for i in range(2, argc):
        if i in u64_at or i in opt_at:
            args.append(rng.choice(U64_VALID))
        elif i == 2:
            args.append(rng.choice(KEYS))
        elif values is not None and i == argc - 1:
            args.append(rng.choice(values))
        elif pathy:
            args.append(rng.choice(PATH_PARTS))
        else:
            args.append(rng.choice(KEYS))
    if roll < 0.70:
        return args
    if roll < 0.85:  # boundary: extremes in place of the friendly pools
        for i in range(2, len(args)):
            if i in u64_at or i in opt_at:
                args[i] = rng.choice(
                    [b"0", b"18446744073709551615", b"007"]
                )
            elif i == 2:
                args[i] = rng.choice([b"", b"x" * 300, b"\x00\xff\r\n"])
        return args
    # mutated-invalid: both paths must converge on the same help text
    mutation = rng.randrange(5)
    if mutation == 0 and len(args) > 2:
        args.pop()  # arity short of the grammar
    elif mutation == 1:
        args.append(b"junk")  # extra arg (legal only for path commands)
    elif mutation == 2:
        args[1] = rng.choice([sub.lower().encode(), sub.encode() + b"X"])
    elif mutation == 3 and (u64_at or opt_at):
        idx = rng.choice(sorted(u64_at | opt_at))
        if idx < len(args):
            args[idx] = rng.choice(U64_INVALID)
    elif mutation == 4:
        if values is not None and len(args) > 2:
            args[-1] = rng.choice(BAD_JSON)
        elif pathy:
            args.append(BAD_PATH)
        else:
            args[1] = b"NOPE"
    return args


def gen_streams(
    grammar: dict[str, dict], seed: int, n_streams: int, cmds_per_stream: int
) -> list[list[list[bytes]]]:
    """Deterministic [stream][command][arg] bytes from the grammar."""
    items = sorted(grammar.items())
    streams = []
    for s in range(n_streams):
        rng = random.Random((seed << 16) + s)
        stream = []
        for _ in range(cmds_per_stream):
            key, g = items[rng.randrange(len(items))]
            stream.append(_gen_args(rng, key, g))
        streams.append(stream)
    return streams


def encode_stream(stream: list[list[bytes]]) -> bytes:
    """RESP-array wire encoding of a command stream."""
    out = bytearray()
    for args in stream:
        out += b"*%d\r\n" % len(args)
        for a in args:
            out += b"$%d\r\n%s\r\n" % (len(a), a)
    return bytes(out)


def stream_sha(stream: list[list[bytes]]) -> str:
    return hashlib.sha256(encode_stream(stream)).hexdigest()


def grammar_from_manifest(manifest: dict) -> dict[str, dict]:
    """The generation-relevant native grammar, baked into the harness."""
    out: dict[str, dict] = {}
    for key, rec in manifest["commands"].items():
        nat = rec["native"]
        out[key] = {
            "min_argc": nat["min_argc"],
            "u64_args": nat["u64_args"],
            "opt_u64_args": nat["opt_u64_args"],
            "validators": nat["validators"],
        }
    return out


def run_stream_differential(stream: list[list[bytes]], split: int = 3) -> None:
    """Drive one stream through the full Server twice — native engine
    vs forced-Python — and assert byte-identical replies. The client
    half-closes after sending, so the server's read loop drains every
    buffered command, flushes, and closes: read-to-EOF is the complete
    reply stream with no timeouts."""
    import asyncio

    wire = encode_stream(stream)
    cuts = sorted(
        {1 + (len(wire) * i) // (split + 1) for i in range(1, split + 1)}
    )
    packets = [wire[a:b] for a, b in zip([0] + cuts, cuts + [len(wire)])]

    async def run_one(force_python: bool) -> bytes:
        from jylis_tpu.models.database import Database
        from jylis_tpu.server.server import Server
        from jylis_tpu.utils.config import Config
        from jylis_tpu.utils.log import Log

        cfg = Config()
        cfg.port = "0"
        cfg.log = Log.create_none()
        # jlint: blocking-ok — differential-fuzz harness: the one-off
        # server boot may touch the native loader's listdir, and this
        # throwaway loop runs nothing else concurrently
        db = Database(identity=1, engine="python" if force_python else "auto")
        server = Server(cfg, db)
        await server.start()
        try:
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", server.port
            )
            for p in packets:
                writer.write(p)
                await writer.drain()
            writer.write_eof()
            out = b""
            while True:
                chunk = await reader.read(1 << 20)
                if not chunk:
                    break
                out += chunk
            writer.close()
            return out
        finally:
            await server.dispose()

    native = asyncio.run(run_one(False))
    oracle = asyncio.run(run_one(True))
    assert native == oracle, (
        f"semantic divergence (stream sha {stream_sha(stream)[:12]}): "
        f"native reply bytes != oracle reply bytes\n"
        f"native: {native[:400]!r}\noracle: {oracle[:400]!r}"
    )


# tier-1 budget: tiny but real; the deep sweep rides -m soak
TIER1_STREAMS = 3
TIER1_CMDS = 60
SOAK_STREAMS = 25
SOAK_CMDS = 200
DEFAULT_SEED = 1107


def write_corpus(manifest: dict, manifest_sha256: str,
                 path: str = CORPUS_PATH, seed: int = DEFAULT_SEED) -> dict:
    grammar = grammar_from_manifest(manifest)
    streams = gen_streams(grammar, seed, TIER1_STREAMS, TIER1_CMDS)
    corpus = {
        "_comment": (
            "Golden semantic-fuzz corpus — regenerate with `python -m "
            "scripts.jlint --write-corpus` after any semantics_manifest "
            "change (tests/test_semantic_fuzz.py fails on a manifest "
            "edit that was not re-recorded). Streams are derived from "
            "the manifest grammar with random.Random; shas pin both the "
            "generator and the grammar."
        ),
        "manifest_sha256": manifest_sha256,
        "seed": seed,
        "streams": [
            {"sha256": stream_sha(s), "n_cmds": len(s)} for s in streams
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(corpus, f, indent=2, sort_keys=True)
        f.write("\n")
    return corpus


_HARNESS_TEMPLATE = '''\
"""Differential semantic fuzz — GENERATED, do not edit by hand.

Generated by scripts/gen_semfuzz.py (via `python -m scripts.jlint
--write-manifest`) from scripts/jlint/semantics_manifest.json; jlint
pass 11 fails (JL1103) when this file does not match a fresh render.
Command streams are derived from the extracted argument grammar of
every natively-served command and driven through the full Server twice
(native engine vs forced-Python oracle) with byte-compared replies —
valid, boundary and mutated-invalid commands alike (the engine defers
every error to the oracle, so help text must byte-match too).

The golden corpus (tests/golden/semfuzz_corpus.json) pins the
generation seed, each stream's sha256, and the manifest's own sha256:
editing the manifest without `--write-corpus` fails here in tier-1.
The deep sweep rides `-m soak`.
"""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from scripts import gen_semfuzz  # noqa: E402
from scripts.jlint import pass_semantics  # noqa: E402

GRAMMAR = {grammar}

SEED = {seed}


def _corpus() -> dict:
    with open(gen_semfuzz.CORPUS_PATH, encoding="utf-8") as f:
        return json.load(f)


def test_semfuzz_corpus_pins_manifest_and_streams():
    corpus = _corpus()
    assert corpus["manifest_sha256"] == pass_semantics.manifest_sha(), (
        "semantics_manifest.json changed without re-recording the fuzz "
        "corpus — run `python -m scripts.jlint --write-corpus`, review "
        "the stream shas, commit"
    )
    assert corpus["seed"] == SEED
    streams = gen_semfuzz.gen_streams(
        GRAMMAR, corpus["seed"], gen_semfuzz.TIER1_STREAMS,
        gen_semfuzz.TIER1_CMDS,
    )
    pinned = corpus["streams"]
    assert len(streams) == len(pinned)
    for s, p in zip(streams, pinned):
        assert len(s) == p["n_cmds"]
        assert gen_semfuzz.stream_sha(s) == p["sha256"], (
            "generated stream diverged from the golden corpus — the "
            "generator or grammar changed; re-record with --write-corpus"
        )


@pytest.mark.parametrize("idx", range(gen_semfuzz.TIER1_STREAMS))
def test_semfuzz_differential_tier1(idx):
    corpus = _corpus()
    streams = gen_semfuzz.gen_streams(
        GRAMMAR, corpus["seed"], gen_semfuzz.TIER1_STREAMS,
        gen_semfuzz.TIER1_CMDS,
    )
    gen_semfuzz.run_stream_differential(streams[idx])


@pytest.mark.soak
@pytest.mark.slow
def test_semfuzz_differential_soak():
    corpus = _corpus()
    streams = gen_semfuzz.gen_streams(
        GRAMMAR, corpus["seed"] + 1, gen_semfuzz.SOAK_STREAMS,
        gen_semfuzz.SOAK_CMDS,
    )
    for stream in streams:
        gen_semfuzz.run_stream_differential(stream, split=7)
'''


def render_harness(manifest: dict) -> str:
    grammar = grammar_from_manifest(manifest)
    lines = ["{"]
    for key in sorted(grammar):
        g = grammar[key]
        lines.append(
            f"    {key!r}: {{'min_argc': {g['min_argc']}, "
            f"'u64_args': {g['u64_args']}, "
            f"'opt_u64_args': {g['opt_u64_args']}, "
            f"'validators': {g['validators']}}},"
        )
    lines.append("}")
    return _HARNESS_TEMPLATE.format(
        grammar="\n".join(lines), seed=DEFAULT_SEED
    )
