"""Module / call graph for the jlint semantic core (scripts/jlint/core.py).

Passes 1-6 were six independent token-level walks: each saw one function
at a time, so anything that crossed a call boundary — blocking I/O two
frames below an ``async def``, a lock acquired inside a callee while the
caller already holds another — was invisible (JL104's journal-rotation
stall was exactly that shape). This module builds the project-wide view
those checks need:

* **module graph**: every source file under the analysis scope becomes a
  :class:`ModuleInfo` with its import table resolved *within the
  project* (``from ..cluster import codec`` → ``jylis_tpu/cluster/
  codec.py``). Imports that leave the project (stdlib, jax, numpy)
  resolve to nothing — the analyses treat them as opaque.
* **symbol tables**: per module, the classes (with base-class names and
  an attribute-type map inferred from ``self.x = ClassName(...)``
  assignments) and module-level functions.
* **call resolution**: a best-effort, *no-false-edge* discipline. A call
  is resolved only when the receiver is certain: ``self.m()`` /
  ``cls.m()`` (searching project base classes), a module-level or
  imported function, ``module.func()`` through the import table, or
  ``obj.m()`` where ``obj`` is a local/attribute whose type was pinned
  by a direct constructor assignment. Everything else yields no edge —
  the consumers (blocking closure, lock graph) prefer missing an edge
  to inventing one.

The graph is rebuilt per run from the content-hash-cached ASTs
(core.py); at repo scale this is milliseconds.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from . import Source, dotted_name


def rel_to_module(rel: str) -> str:
    """'jylis_tpu/cluster/codec.py' -> 'jylis_tpu.cluster.codec'."""
    mod = rel[:-3] if rel.endswith(".py") else rel
    mod = mod.replace(os.sep, ".").replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


@dataclass
class ClassInfo:
    name: str
    rel: str
    bases: list[str] = field(default_factory=list)  # names as written
    methods: dict[str, ast.AST] = field(default_factory=dict)
    # self.<attr> = <ClassName>(...) constructor assignments: attr ->
    # class name as written (resolved lazily through the import table)
    attr_types: dict[str, str] = field(default_factory=dict)

    def qual(self, method: str) -> str:
        return f"{self.rel}::{self.name}.{method}"


@dataclass
class ModuleInfo:
    rel: str
    modname: str
    # import table: local alias -> project module name ('codec' ->
    # 'jylis_tpu.cluster.codec'); only project-internal targets kept
    imports: dict[str, str] = field(default_factory=dict)
    # from-import table: local name -> (project module name, symbol)
    from_imports: dict[str, tuple[str, str]] = field(default_factory=dict)
    functions: dict[str, ast.AST] = field(default_factory=dict)
    classes: dict[str, ClassInfo] = field(default_factory=dict)


class ProjectGraph:
    """Module + symbol tables over a set of Sources, with call resolution."""

    def __init__(self, sources: list[Source]):
        self.modules: dict[str, ModuleInfo] = {}  # modname -> info
        self.by_rel: dict[str, ModuleInfo] = {}
        for src in sources:
            mi = self._index_module(src)
            self.modules[mi.modname] = mi
            self.by_rel[mi.rel] = mi
        # class name -> [ClassInfo] (cross-module base-class lookup)
        self.classes_by_name: dict[str, list[ClassInfo]] = {}
        for mi in self.modules.values():
            for ci in mi.classes.values():
                self.classes_by_name.setdefault(ci.name, []).append(ci)

    # ---- indexing ----------------------------------------------------------

    def _index_module(self, src: Source) -> ModuleInfo:
        mi = ModuleInfo(rel=src.rel, modname=rel_to_module(src.rel))
        pkg_parts = mi.modname.split(".")[:-1]
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.asname or alias.name.split(".")[0]
                    target = alias.name if alias.asname else alias.name.split(".")[0]
                    mi.imports[name] = target
            elif isinstance(node, ast.ImportFrom):
                base = self._resolve_from(node, pkg_parts)
                if base is None:
                    continue
                for alias in node.names:
                    name = alias.asname or alias.name
                    # `from pkg import mod` can import a MODULE: prefer
                    # that reading when pkg.mod exists in the project
                    submod = f"{base}.{alias.name}"
                    if self._project_has(submod):
                        mi.imports[name] = submod
                    else:
                        mi.from_imports[name] = (base, alias.name)
        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mi.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                ci = ClassInfo(name=node.name, rel=src.rel)
                for b in node.bases:
                    nm = dotted_name(b)
                    if nm:
                        ci.bases.append(nm.split(".")[-1])
                for m in node.body:
                    if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        ci.methods[m.name] = m
                        self._scan_attr_types(m, ci)
                mi.classes[node.name] = ci
        return mi

    def _scan_attr_types(self, m: ast.AST, ci: ClassInfo) -> None:
        """self.<attr> = ClassName(...) pins the attribute's type (the
        alias-tracking seed: `self._journal.close()` then resolves into
        Journal.close)."""
        for node in ast.walk(m):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            t = node.targets[0]
            if not (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                continue
            v = node.value
            if isinstance(v, ast.Call):
                cname = dotted_name(v.func).split(".")[-1]
                if cname and cname[0].isupper():
                    ci.attr_types[t.attr] = cname

    def _resolve_from(self, node: ast.ImportFrom, pkg_parts: list[str]) -> str | None:
        if node.level == 0:
            return node.module
        # relative import: climb `level` packages from this module's package
        if node.level > len(pkg_parts):
            return None
        base_parts = pkg_parts[: len(pkg_parts) - (node.level - 1)]
        if node.module:
            base_parts = base_parts + node.module.split(".")
        return ".".join(base_parts)

    def _project_has(self, modname: str) -> bool:
        return modname in self.modules

    # ---- call resolution ---------------------------------------------------

    def resolve_class(self, name: str, mi: ModuleInfo) -> ClassInfo | None:
        """A class NAME as visible from module `mi` (local, from-import,
        unique-in-project fallback for base classes)."""
        if name in mi.classes:
            return mi.classes[name]
        fi = mi.from_imports.get(name)
        if fi is not None:
            target = self.modules.get(fi[0])
            if target is not None and fi[1] in target.classes:
                return target.classes[fi[1]]
        cands = self.classes_by_name.get(name, [])
        if len(cands) == 1:
            return cands[0]
        return None

    def method_in_hierarchy(
        self, ci: ClassInfo, method: str, _seen: frozenset = frozenset()
    ) -> str | None:
        """Qualname of `method` on `ci` or its project base classes."""
        if ci.name in _seen:
            return None
        if method in ci.methods:
            return ci.qual(method)
        mi = self.by_rel.get(ci.rel)
        for base in ci.bases:
            bci = self.resolve_class(base, mi) if mi is not None else None
            if bci is not None:
                q = self.method_in_hierarchy(
                    bci, method, _seen | {ci.name}
                )
                if q is not None:
                    return q
        return None

    def resolve_call(
        self,
        func: ast.AST,
        mi: ModuleInfo,
        cls: ClassInfo | None,
        local_types: dict[str, str],
    ) -> tuple[str, ...]:
        """Resolved callee qualname(s) for a call expression, or () when
        the receiver cannot be pinned (no false edges)."""
        # bare name: local function / from-import / class constructor
        if isinstance(func, ast.Name):
            name = func.id
            if name in mi.functions:
                return (f"{mi.rel}::{name}",)
            fi = mi.from_imports.get(name)
            if fi is not None:
                target = self.modules.get(fi[0])
                if target is not None:
                    if fi[1] in target.functions:
                        return (f"{target.rel}::{fi[1]}",)
                    if fi[1] in target.classes:
                        tci = target.classes[fi[1]]
                        if "__init__" in tci.methods:
                            return (tci.qual("__init__"),)
                        return ()
            ci = self.resolve_class(name, mi)
            if ci is not None and name[:1].isupper() and "__init__" in ci.methods:
                return (ci.qual("__init__"),)
            return ()
        if not isinstance(func, ast.Attribute):
            return ()
        parts = dotted_name(func).split(".")
        if len(parts) < 2:
            return ()
        head, meth = parts[0], parts[-1]
        # self.m() / cls.m() — also self.attr.m() via attr_types
        if head in ("self", "cls") and cls is not None:
            if len(parts) == 2:
                q = self.method_in_hierarchy(cls, meth)
                return (q,) if q is not None else ()
            if len(parts) == 3:
                tname = cls.attr_types.get(parts[1])
                if tname is not None:
                    tci = self.resolve_class(tname, mi)
                    if tci is not None:
                        q = self.method_in_hierarchy(tci, meth)
                        return (q,) if q is not None else ()
            return ()
        # module alias: codec.encode() / journal.replay_journal()
        if head in mi.imports and len(parts) == 2:
            target = self.modules.get(mi.imports[head])
            if target is not None and meth in target.functions:
                return (f"{target.rel}::{meth}",)
            if target is not None and meth in target.classes:
                tci = target.classes[meth]
                if "__init__" in tci.methods:
                    return (tci.qual("__init__"),)
            return ()
        # local variable with a constructor-pinned type: j = Journal(...)
        if head in local_types and len(parts) == 2:
            tci = self.resolve_class(local_types[head], mi)
            if tci is not None:
                q = self.method_in_hierarchy(tci, meth)
                return (q,) if q is not None else ()
        # ClassName.method (direct, e.g. for staticmethod-style calls)
        ci = self.resolve_class(head, mi)
        if ci is not None and head[:1].isupper() and len(parts) == 2:
            q = self.method_in_hierarchy(ci, meth)
            return (q,) if q is not None else ()
        return ()
