"""Pass 3 — RESP surface parity (rules JL301/JL302).

PR 2 settled the full steady-state command surface of all five data
types in the native engine, checked against the Python oracle by
hand-written differential fuzz. Nothing prevented drift: a command
class added to ``native/serve_engine.cpp`` without a matching oracle
path in ``models/repo_*.py`` (or vice versa) would ship silently and
surface as a wire-level divergence between serving paths.

This pass extracts both dispatch surfaces mechanically:

* native: the ``word_is(buf, offs[0], …, "TYPE")`` /
  ``word_is(buf, offs[1], …, "SUB")`` guards in ``serve_engine.cpp``
  (the counter block shares GCOUNT/PNCOUNT dispatch; a ``which == 1``
  qualifier restricts a subcommand to PNCOUNT);
* python: the ``op == b"SUB"`` comparisons inside each repo class's
  ``apply`` method, keyed by the class's ``name`` attribute.

They are folded into a committed manifest
(``scripts/jlint/parity_manifest.json``):

* ``native`` / ``python``: the extracted surfaces;
* ``python_only``: commands the oracle serves that the engine defers
  by design (TLOG TRIM/TRIMAT/CLR dispatch device drains; SYSTEM is
  host-only) — every such command must be listed here, so going
  native-first is always a conscious, reviewed change.

JL301 fires when a command is served natively with no Python oracle
path, or a Python command is neither native nor listed python-only.
JL302 fires when the committed manifest differs from the extracted
surfaces — ``python -m scripts.jlint --write-manifest`` regenerates it,
and the git diff is the review surface.
"""

from __future__ import annotations

import ast
import json
import os
import re

from . import Finding, MANIFEST_PATH, ROOT

SERVE_ENGINE = os.path.join(ROOT, "native", "serve_engine.cpp")
REPO_GLOB_DIR = os.path.join(ROOT, "jylis_tpu", "models")

_TYPE_RE = re.compile(r'word_is\(buf,\s*offs\[0\],\s*lens\[0\],\s*"(\w+)"\)')
_SUB_RE = re.compile(r'word_is\(buf,\s*offs\[1\],\s*lens\[1\],\s*"(\w+)"\)')


def extract_native(path: str = SERVE_ENGINE) -> dict[str, list[str]]:
    """{TYPE: sorted [SUB]} from the engine's dispatch guards."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    events: list[tuple[int, str, str]] = []
    for m in _TYPE_RE.finditer(text):
        events.append((m.start(), "type", m.group(1)))
    for m in _SUB_RE.finditer(text):
        events.append((m.start(), "sub", m.group(1)))
    events.sort()
    surface: dict[str, set[str]] = {}
    active: list[str] = []
    last_kind = None
    for pos, kind, word in events:
        if kind == "type":
            if last_kind == "type":
                active.append(word)  # adjacent guards share one block
            else:
                active = [word]
            surface.setdefault(word, set())
        else:
            # a `which == 1 && … word_is(…)` qualifier in the shared
            # counter block restricts the subcommand to PNCOUNT
            window = text[max(0, pos - 200) : pos]
            stmt = window.rsplit(";", 1)[-1]
            targets = active
            if "which == 1" in stmt:
                targets = [t for t in active if t == "PNCOUNT"] or active
            for t in targets:
                surface[t].add(word)
        last_kind = kind
    return {t: sorted(subs) for t, subs in sorted(surface.items())}


def extract_python(models_dir: str = REPO_GLOB_DIR) -> dict[str, list[str]]:
    """{TYPE: sorted [SUB]} from every repo class's `apply` dispatch."""
    surface: dict[str, set[str]] = {}
    for fname in sorted(os.listdir(models_dir)):
        if not (fname.startswith("repo_") and fname.endswith(".py")):
            continue
        path = os.path.join(models_dir, fname)
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            name = None
            for stmt in cls.body:
                if (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "name"
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, str)
                ):
                    name = stmt.value.value
            apply_fn = next(
                (
                    m for m in cls.body
                    if isinstance(m, ast.FunctionDef) and m.name == "apply"
                ),
                None,
            )
            if name is None or apply_fn is None:
                continue
            subs = surface.setdefault(name, set())
            for node in ast.walk(apply_fn):
                if not isinstance(node, ast.Compare):
                    continue
                operands = [node.left] + list(node.comparators)
                # `op in (b"INC", b"DEC")` dispatches through a tuple:
                # unpack container comparators into their elements
                flat: list[ast.expr] = []
                for o in operands:
                    if isinstance(o, (ast.Tuple, ast.List, ast.Set)):
                        flat.extend(o.elts)
                    else:
                        flat.append(o)
                operands = flat
                consts = [
                    o.value for o in operands
                    if isinstance(o, ast.Constant) and isinstance(o.value, bytes)
                ]
                names = [
                    o.id for o in operands if isinstance(o, ast.Name)
                ]
                if consts and ("op" in names or any(
                    isinstance(o, ast.Subscript) for o in operands
                )):
                    for c in consts:
                        word = c.decode("ascii", "replace")
                        if word.isupper() and word.isalpha():
                            subs.add(word)
    return {t: sorted(subs) for t, subs in sorted(surface.items())}


def build_manifest(
    native: dict[str, list[str]] | None = None,
    python: dict[str, list[str]] | None = None,
) -> dict:
    native = native if native is not None else extract_native()
    python = python if python is not None else extract_python()
    python_only: dict[str, list[str]] = {}
    for t, subs in python.items():
        nat = set(native.get(t, []))
        only = sorted(set(subs) - nat)
        if only:
            python_only[t] = only
    return {
        "_comment": (
            "Generated by `python -m scripts.jlint --write-manifest` from "
            "native/serve_engine.cpp and jylis_tpu/models/repo_*.py — do "
            "not edit by hand. `make lint` fails on drift (JL302) and on "
            "any natively-served command with no Python oracle path "
            "(JL301)."
        ),
        "native": native,
        "python": python,
        "python_only": python_only,
    }


def write_manifest(path: str = MANIFEST_PATH) -> dict:
    manifest = build_manifest()
    with open(path, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
        f.write("\n")
    return manifest


def check(
    manifest_path: str = MANIFEST_PATH,
    native: dict[str, list[str]] | None = None,
    python: dict[str, list[str]] | None = None,
) -> list[Finding]:
    out: list[Finding] = []
    current = build_manifest(native, python)
    rel = os.path.relpath(manifest_path, ROOT)

    # JL301: native without oracle / python neither native nor declared
    for t, subs in current["native"].items():
        py = set(current["python"].get(t, []))
        for sub in subs:
            if sub not in py:
                out.append(
                    Finding(
                        "JL301", "native/serve_engine.cpp", 1,
                        f"`{t} {sub}` is served natively but has no Python "
                        "oracle path in models/ — the oracle defines the "
                        "semantics; add the Python path first",
                        f"{t} {sub}",
                    )
                )
    for t, subs in current["python"].items():
        nat = set(current["native"].get(t, []))
        declared = set(current["python_only"].get(t, []))
        for sub in subs:
            if sub not in nat and sub not in declared:
                out.append(
                    Finding(
                        "JL301", rel, 1,
                        f"`{t} {sub}` exists in Python but is neither served "
                        "natively nor listed python_only in the manifest",
                        f"{t} {sub}",
                    )
                )

    # JL302: committed manifest drift
    if not os.path.exists(manifest_path):
        out.append(
            Finding(
                "JL302", rel, 1,
                "parity manifest missing — run `python -m scripts.jlint "
                "--write-manifest` and commit it",
                "",
            )
        )
        return out
    with open(manifest_path, encoding="utf-8") as f:
        committed = json.load(f)
    for key in ("native", "python", "python_only"):
        if committed.get(key) != current[key]:
            out.append(
                Finding(
                    "JL302", rel, 1,
                    f"parity manifest drift in `{key}`: committed "
                    f"{json.dumps(committed.get(key), sort_keys=True)} != "
                    f"extracted {json.dumps(current[key], sort_keys=True)} — "
                    "run `python -m scripts.jlint --write-manifest`, review "
                    "the diff, commit",
                    key,
                )
            )
    return out
