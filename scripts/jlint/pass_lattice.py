"""Pass 8 — CRDT lattice-law discipline (rules JL801-JL805).

Convergence and digest-matching rest on four properties of every merge
path that no test suite can prove and one stray line can break: joins
must be commutative, associative, idempotent, and free of wall-clock or
iteration-order dependence. This pass holds the STATIC half of that
contract over ``jylis_tpu/models/`` and ``jylis_tpu/ops/`` (+ the wire
encoders that feed digests), using the interprocedural core:

* **JL801** — a wall-clock read (``time.time`` / ``time.time_ns`` /
  ``now_ms`` / ``datetime.now``) reachable from any merge/join/apply
  path (call-graph closure over the core's resolved edges). Timestamps
  in the lattice come from CLIENTS (or the one documented SYSTEM
  minting site); a join that reads the clock diverges replicas.
  Suppress with ``# jlint: wallclock-ok — <why>`` at the root.
* **JL802** — unordered ``dict``/``set`` iteration
  (``.items()/.keys()/.values()``) feeding a digest canon, a wire
  encoding, or a flush, without ``sorted()`` (or another
  order-insensitive consumer: ``sum``/``min``/``max``/``len``/
  ``set``/``any``/``all``). Two converged replicas with different
  insertion histories iterate differently; bytes derived from that
  iteration diverge. Suppress with ``# jlint: order-ok — <why>`` (e.g.
  the native encoder sorts on the wire).
* **JL803** — in-place mutation of a batch/delta object AFTER it
  aliased into a sink (``journal.append``, ``broadcast_deltas``, a held
  queue): the sink's consumer sees the mutated object — flush output
  must be export-then-freeze. Intraprocedural dataflow: a name passed
  to a sink is poisoned for the rest of the function; any mutating
  method/subscript-store on it fires. Suppress ``# jlint: alias-ok``.
* **JL804** — a replica-id-dependent branch inside a join path: two
  replicas joining identical states must take identical branches, or
  the lattice is not a lattice. The deliberate own-column repairs in
  ``load_state`` carry ``# jlint: ridbranch-ok — <why>``.
* **JL805** — lattice manifest / property-harness drift (the dynamic
  half): ``scripts/jlint/lattice_manifest.json`` records each rule's
  obligation, the extracted merge-root inventory, and the five types'
  harness bindings; ``tests/test_lattice_laws.py`` is GENERATED from
  that manifest (``--write-manifest`` regenerates both) and runs the
  three join laws over seeded random delta pairs per type in tier-1 —
  the static rules and the dynamic laws pin each other.
"""

from __future__ import annotations

import ast
import json
import os

from . import Finding, ROOT, dotted_name

LATTICE_MANIFEST_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "lattice_manifest.json"
)
HARNESS_PATH = os.path.join(ROOT, "tests", "test_lattice_laws.py")

SCOPE_PREFIXES = (
    os.path.join("jylis_tpu", "models"),
    os.path.join("jylis_tpu", "ops"),
)

# function names that constitute a merge/join/apply path root
MERGE_ROOT_NAMES = ("join", "fold_in", "load_state", "apply")
MERGE_ROOT_PREFIXES = ("converge", "join", "merge")

WALL_CLOCK = {
    "time.time", "time.time_ns", "now_ms", "_now_ms",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
}

# functions whose output becomes digest canon / wire bytes / flush export
ORDER_SENSITIVE_FUNCS = ("sync_canon", "dump_state", "flush_deltas")
ORDER_SAFE_WRAPPERS = {
    "sorted", "sum", "min", "max", "len", "set", "frozenset", "any", "all",
}

SINK_RECEIVERS = ("journal", "held")
SINK_FUNCS = ("broadcast_deltas",)
MUTATORS = {
    "append", "appendleft", "extend", "insert", "remove", "pop", "popleft",
    "clear", "update", "setdefault", "add", "discard", "sort", "reverse",
}

RID_MARKERS = ("identity", "replica_id", "_rid")

PLACEHOLDER = "(describe this obligation)"

# the static obligations, one per rule — preserved across regeneration
DEFAULT_RULES = {
    "JL801": (
        "No wall-clock read may be reachable from a merge/join/apply "
        "path: lattice timestamps come from clients (or the one "
        "documented SYSTEM minting site); a join that reads the clock "
        "diverges replicas."
    ),
    "JL802": (
        "No unordered dict/set iteration may feed a digest canon, a "
        "wire encoding, or a flush export without sorted() — converged "
        "replicas iterate in different orders and the derived bytes "
        "diverge."
    ),
    "JL803": (
        "A delta/batch object that aliased into a sink (journal append, "
        "broadcast, held queue) is frozen: later in-place mutation "
        "reaches the sink's consumer — export-then-freeze."
    ),
    "JL804": (
        "No replica-id-dependent branch inside a join path: two "
        "replicas joining identical states must take identical "
        "branches (own-column boot repairs in load_state are the "
        "reviewed exception)."
    ),
}

# the five types' dynamic-law harness bindings: lattice import path,
# canonical-form recipe, and generator name (rendered into
# tests/test_lattice_laws.py by write_harness)
HARNESS_TYPES = {
    "TREG": {"lattice": "jylis_tpu.ops.hostref:TReg", "gen": "gen_treg"},
    "TLOG": {"lattice": "jylis_tpu.ops.hostref:TLog", "gen": "gen_tlog"},
    "GCOUNT": {"lattice": "jylis_tpu.ops.hostref:GCounter", "gen": "gen_gcount"},
    "PNCOUNT": {"lattice": "jylis_tpu.ops.hostref:PNCounter", "gen": "gen_pncount"},
    "UJSON": {"lattice": "jylis_tpu.ops.ujson_host:UJSON", "gen": "gen_ujson"},
    "TENSOR": {"lattice": "jylis_tpu.ops.tensor_host:Tensor", "gen": "gen_tensor"},
    "BCOUNT": {"lattice": "jylis_tpu.ops.bcount:BCount", "gen": "gen_bcount"},
}
# MAP is NOT a static row: the rendered harness expands one MAP[inner]
# row PER REGISTERED inner lattice at import time (ops/compose.REGISTRY),
# so registering a new value type auto-generates its composed join laws
# with no manifest edit. BCOUNT additionally carries the escrow-safety
# law (random locally-checked histories never break 0 <= value <= bound).


def _in_scope(rel: str) -> bool:
    return rel.startswith(SCOPE_PREFIXES)


def _is_merge_root(name: str) -> bool:
    return name in MERGE_ROOT_NAMES or name.startswith(MERGE_ROOT_PREFIXES)


def merge_roots(project) -> list:
    """Every merge/join/apply entry point in models/ and ops/."""
    return sorted(
        (fi for fi in project.functions.values()
         if _in_scope(fi.rel) and _is_merge_root(fi.name)),
        key=lambda fi: fi.qual,
    )


# ---- JL801: wall-clock reachability ----------------------------------------


def _clock_closure(project) -> dict[str, tuple[str, ...]]:
    """qual -> witness chain to a wall-clock read, transitively over
    resolved call edges (async and sync alike: a clocked coroutine in a
    join path is just as divergent)."""
    closure: dict[str, tuple[str, ...]] = {}
    for q, fi in project.functions.items():
        for site in fi.calls:
            raw_tail = site.raw.split(".")[-1] if site.raw else ""
            if site.raw in WALL_CLOCK or raw_tail in ("now_ms", "_now_ms") or (
                raw_tail in ("time", "time_ns") and site.raw.startswith("time.")
            ):
                closure[q] = (site.raw,)
                break
    changed = True
    while changed:
        changed = False
        for q, fi in project.functions.items():
            if q in closure:
                continue
            for site in fi.calls:
                for t in site.targets:
                    if t in closure:
                        closure[q] = (t,) + closure[t]
                        changed = True
                        break
                if q in closure:
                    break
    return closure


def check_wall_clock(project) -> list[Finding]:
    out: list[Finding] = []
    closure = _clock_closure(project)
    for fi in merge_roots(project):
        chain = closure.get(fi.qual)
        if chain is None:
            continue
        src = project.by_rel.get(fi.rel)
        out.append(
            Finding(
                "JL801", fi.rel, fi.lineno,
                f"merge path `{fi.qual.split('::', 1)[1]}` reaches a "
                f"wall-clock read via {' -> '.join(chain)} — joins must "
                "not depend on local time; suppress only with a "
                "documented minting-site justification",
                src.line_src(fi.lineno) if src is not None else "",
            )
        )
    return out


# ---- JL802: unordered iteration feeding digest/wire/flush ------------------


def _order_sensitive_functions(project):
    for fi in project.functions.values():
        if not (_in_scope(fi.rel) or "codec.py" in fi.rel):
            continue
        if fi.name in ORDER_SENSITIVE_FUNCS or (
            fi.name.startswith("_w_") and "cluster" in fi.rel
        ) or (
            fi.name.startswith("_encode_") and "native" in fi.rel
        ) or fi.name == "_sync_update_repo":
            yield fi


def check_iteration_order(project) -> list[Finding]:
    out: list[Finding] = []
    for fi in _order_sensitive_functions(project):
        src = project.by_rel.get(fi.rel)
        # every .items()/.keys()/.values() call whose IMMEDIATE consumer
        # is not order-insensitive
        safe_args: set[int] = set()
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                fname = dotted_name(node.func).split(".")[-1]
                if fname in ORDER_SAFE_WRAPPERS:
                    for a in ast.walk(node):
                        safe_args.add(id(a))
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            if node.func.attr not in ("items", "keys", "values"):
                continue
            if node.args or node.keywords:
                continue  # not the dict-view idiom
            if id(node) in safe_args:
                continue
            recv = dotted_name(node.func.value) or "<expr>"
            out.append(
                Finding(
                    "JL802", fi.rel, node.lineno,
                    f"`{recv}.{node.func.attr}()` iterates in insertion "
                    f"order inside `{fi.name}`, which feeds a digest/"
                    "wire/flush — wrap in sorted() or justify with "
                    "`# jlint: order-ok`",
                    src.line_src(node.lineno) if src is not None else "",
                )
            )
    return out


# ---- JL803: mutation after aliasing into a sink ----------------------------


def _sink_args(call: ast.Call) -> list[str]:
    """Names aliased into a sink by this call, or []."""
    fname = dotted_name(call.func)
    tail = fname.split(".")[-1]
    names: list[str] = []
    is_sink = False
    if tail in SINK_FUNCS:
        is_sink = True
    elif tail == "append" and isinstance(call.func, ast.Attribute):
        recv = dotted_name(call.func.value).lower()
        if any(s in recv for s in SINK_RECEIVERS):
            is_sink = True
    if not is_sink:
        return names
    for a in call.args:
        for n in ast.walk(a):
            if isinstance(n, ast.Name):
                names.append(n.id)
            elif (
                isinstance(n, ast.Attribute)
                and isinstance(n.value, ast.Name)
                and n.value.id == "self"
            ):
                names.append(f"self.{n.attr}")
    return names


def check_sink_aliasing(project) -> list[Finding]:
    out: list[Finding] = []
    for fi in project.functions.values():
        if not (_in_scope(fi.rel) or "cluster" in fi.rel or "journal" in fi.rel):
            continue
        src = project.by_rel.get(fi.rel)
        # ordered walk: (line, kind, payload)
        events: list[tuple[int, str, object]] = []
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                aliased = _sink_args(node)
                if aliased:
                    events.append((node.lineno, "sink", aliased))
                elif isinstance(node.func, ast.Attribute) and (
                    node.func.attr in MUTATORS
                ):
                    tgt = node.func.value
                    name = None
                    if isinstance(tgt, ast.Name):
                        name = tgt.id
                    elif (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        name = f"self.{tgt.attr}"
                    if name is not None:
                        events.append((node.lineno, "mutate", name))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        base = t.value
                        if isinstance(base, ast.Name):
                            events.append((node.lineno, "mutate", base.id))
                        elif (
                            isinstance(base, ast.Attribute)
                            and isinstance(base.value, ast.Name)
                            and base.value.id == "self"
                        ):
                            events.append(
                                (node.lineno, "mutate", f"self.{base.attr}")
                            )
        events.sort(key=lambda e: e[0])
        poisoned: dict[str, int] = {}
        for line, kind, payload in events:
            if kind == "sink":
                for name in payload:
                    poisoned.setdefault(name, line)
            elif kind == "mutate" and payload in poisoned:
                out.append(
                    Finding(
                        "JL803", fi.rel, line,
                        f"`{payload}` aliased into a journal/broadcast/"
                        f"held sink at line {poisoned[payload]} and is "
                        f"mutated in place here — the sink's consumer "
                        "sees the mutation; copy before mutating or "
                        "justify with `# jlint: alias-ok`",
                        src.line_src(line) if src is not None else "",
                    )
                )
    return out


# ---- JL804: replica-id-dependent branches in joins -------------------------


def check_rid_branches(project) -> list[Finding]:
    out: list[Finding] = []
    for fi in merge_roots(project):
        if fi.name == "apply":
            continue  # command dispatch handles per-replica ops by design
        src = project.by_rel.get(fi.rel)
        for node in ast.walk(fi.node):
            if not isinstance(node, (ast.If, ast.IfExp)):
                continue
            test_src = ast.unparse(node.test).lower()
            if any(m in test_src for m in RID_MARKERS):
                out.append(
                    Finding(
                        "JL804", fi.rel, node.lineno,
                        f"replica-id-dependent branch inside merge path "
                        f"`{fi.name}` — two replicas joining identical "
                        "states must take identical branches; justify "
                        "with `# jlint: ridbranch-ok` if this is the "
                        "documented own-column repair",
                        src.line_src(node.lineno) if src is not None else "",
                    )
                )
    return out


# ---- manifest + generated property harness ---------------------------------


def extract_roots(project) -> list[str]:
    return [fi.qual for fi in merge_roots(project)]


def load_manifest(path: str = LATTICE_MANIFEST_PATH) -> dict:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def build_manifest(project) -> dict:
    existing = load_manifest()
    rules = {
        rule: existing.get("rules", {}).get(rule, default)
        for rule, default in DEFAULT_RULES.items()
    }
    return {
        "_comment": (
            "Generated by `python -m scripts.jlint --write-manifest`. "
            "`rules` documents each JL80x obligation (human-edited, "
            "preserved across regeneration); `merge_roots` is the "
            "extracted merge/join/apply inventory the reachability "
            "checks run from; `types` binds each of the five lattices "
            "to the generated property harness "
            "(tests/test_lattice_laws.py, ALSO regenerated by "
            "--write-manifest) that proves join "
            "commutativity/associativity/idempotence dynamically. "
            "`make lint` fails on drift (JL805)."
        ),
        "rules": rules,
        "merge_roots": extract_roots(project),
        "types": HARNESS_TYPES,
    }


def write_manifest(project, path: str = LATTICE_MANIFEST_PATH) -> dict:
    manifest = build_manifest(project)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
        f.write("\n")
    with open(HARNESS_PATH, "w", encoding="utf-8") as f:
        f.write(render_harness(manifest))
    return manifest


def check_manifest(project, path: str = LATTICE_MANIFEST_PATH) -> list[Finding]:
    out: list[Finding] = []
    rel = os.path.relpath(path, ROOT)
    manifest = load_manifest(path)
    if not manifest:
        out.append(
            Finding(
                "JL805", rel, 1,
                "lattice manifest missing — run `python -m scripts.jlint "
                "--write-manifest` and commit it (plus the generated "
                "tests/test_lattice_laws.py)",
                "",
            )
        )
        return out
    current = extract_roots(project)
    committed = manifest.get("merge_roots", [])
    for q in current:
        if q not in committed:
            out.append(
                Finding(
                    "JL805", rel, 1,
                    f"merge root `{q}` is not recorded in the lattice "
                    "manifest — run --write-manifest and review",
                    q,
                )
            )
    for q in committed:
        if q not in current:
            out.append(
                Finding(
                    "JL805", rel, 1,
                    f"stale lattice manifest merge root `{q}`: no such "
                    "function — run --write-manifest",
                    q,
                )
            )
    for rule in DEFAULT_RULES:
        desc = manifest.get("rules", {}).get(rule, "")
        if not desc.strip() or desc.strip() == PLACEHOLDER:
            out.append(
                Finding(
                    "JL805", rel, 1,
                    f"lattice rule `{rule}` has no documented obligation "
                    "in the manifest",
                    rule,
                )
            )
    if manifest.get("types") != HARNESS_TYPES:
        out.append(
            Finding(
                "JL805", rel, 1,
                "lattice manifest `types` table drifted from the harness "
                "bindings — run --write-manifest",
                "types",
            )
        )
    # the committed harness must be exactly what the manifest renders
    try:
        with open(HARNESS_PATH, encoding="utf-8") as f:
            committed_harness = f.read()
    except OSError:
        committed_harness = None
    rendered = render_harness(
        {"rules": manifest.get("rules", {}), "merge_roots": committed,
         "types": manifest.get("types", {})}
    )
    if committed_harness != rendered:
        out.append(
            Finding(
                "JL805", os.path.relpath(HARNESS_PATH, ROOT), 1,
                "tests/test_lattice_laws.py is stale: it is generated "
                "from the lattice manifest — run `python -m scripts.jlint "
                "--write-manifest` and commit the regenerated harness",
                "",
            )
        )
    return out


def run(project) -> list[Finding]:
    out = check_wall_clock(project)
    out += check_iteration_order(project)
    out += check_sink_aliasing(project)
    out += check_rid_branches(project)
    return out


# ---- harness template ------------------------------------------------------


def render_harness(manifest: dict) -> str:
    types = manifest.get("types", HARNESS_TYPES)
    type_rows = "\n".join(
        f'    ("{name}", "{spec["lattice"]}", {spec["gen"]}),'
        for name, spec in sorted(types.items())
    )
    return f'''"""GENERATED by `python -m scripts.jlint --write-manifest` from
scripts/jlint/lattice_manifest.json — DO NOT EDIT BY HAND (jlint JL805
fails on drift; edit the manifest/template in scripts/jlint/
pass_lattice.py and regenerate).

The dynamic half of the pass-8 lattice contract: for every CRDT
lattice — the flat types, the BCOUNT escrow counter, and the composed
MAP instantiated over EVERY registered inner lattice
(ops/compose.REGISTRY, expanded at import time so a newly registered
value type auto-generates its composed join laws) — the join must be
commutative, associative, and idempotent over randomly generated delta
states. BCOUNT additionally carries the escrow-safety law: random
concurrent histories in which every spend passed its replica's LOCAL
rights check keep 0 <= value <= bound on every replica's view under
every delivery order. Seeded RNG, no external property-testing
dependency — hypothesis-style shrinking is traded for a fixed,
replayable seed per case.
"""

from __future__ import annotations

import copy
import importlib
import os
import random
import struct
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

N_CASES = 60
SEED = 0x1A771CE


def _lattice(path):
    mod, cls = path.split(":")
    return getattr(importlib.import_module(mod), cls)


def _canon(x):
    """Canonical comparable form per lattice (representation-normal)."""
    name = type(x).__name__
    if name == "GCounter":
        return ("G", tuple(sorted(x.counts.items())))
    if name == "PNCounter":
        return ("PN", _canon(x.p), _canon(x.n))
    if name == "TReg":
        return ("TR", x.is_set, x.ts, x.value)
    if name == "TLog":
        return ("TL", tuple(x.entries), x.cutoff)
    if name == "Tensor":
        # already representation-normal: packed canonical bytes + sorted
        # contribution tuples (tensor_host.Tensor.canon)
        return ("TS",) + x.canon()
    if name == "BCount":
        return ("BC",) + x.canon()
    if name == "MapCRDT":
        return ("MP",) + x.canon()
    # UJSON: entries + fully-compacted causal context
    x.ctx.compact()
    return (
        "UJ",
        tuple(sorted(x.entries.items())),
        tuple(sorted(x.ctx.vv.items())),
        tuple(sorted(x.ctx.cloud)),
    )


def _join(a, b):
    out = copy.deepcopy(a)
    out.converge(copy.deepcopy(b))
    return out


def gen_gcount(rng, cls):
    g = cls()
    for rid in rng.sample(range(1, 9), rng.randint(0, 5)):
        g.counts[rid] = rng.randint(1, 1 << 40)
    return g


def gen_pncount(rng, cls):
    pn = cls()
    pn.p = gen_gcount(rng, type(pn.p))
    pn.n = gen_gcount(rng, type(pn.n))
    return pn


def gen_treg(rng, cls):
    t = cls()
    if rng.random() < 0.85:
        t.write(bytes(rng.choices(b"abcdef", k=rng.randint(0, 4))),
                rng.randint(0, 5))
    return t


def gen_tlog(rng, cls):
    t = cls()
    for _ in range(rng.randint(0, 6)):
        t.insert(bytes(rng.choices(b"xyz", k=rng.randint(1, 3))),
                 rng.randint(0, 9))
    if rng.random() < 0.3:
        t.raise_cutoff(rng.randint(0, 9))
    return t


def gen_ujson(rng, cls):
    u = cls()
    paths = (("a",), ("a", "b"), ("c",))
    tokens = ('"v"', "1", "true")
    for _ in range(rng.randint(0, 5)):
        rid = rng.randint(1, 4)
        seq = rng.randint(1, 6)
        # payload is a FUNCTION of the dot: a dot names one unique event,
        # so two deltas that both carry it must agree on its payload —
        # independent random payloads would violate the CRDT's dot-
        # uniqueness invariant and "fail" laws the lattice does satisfy
        u.entries[(rid, seq)] = (
            paths[(rid + seq) % 3], tokens[(rid * 3 + seq) % 3],
        )
        u.ctx.add((rid, seq))
    for _ in range(rng.randint(0, 3)):
        u.ctx.add((rng.randint(1, 4), rng.randint(1, 6)))
    u.ctx.compact()
    return u


def gen_tensor(rng, cls):
    """Random mode/dim/coordinates, NaN and ±inf included: the lattice
    totalises IEEE order via okey (canonical NaN = per-coordinate top),
    so the laws must hold across the whole float line. Mode and dim
    vary so the (mode, dim) dominance rule is exercised too. AVG
    payloads may collide on (rid, ts) with different vectors — the
    value-bits tiebreak keeps even that adversarial case lawful."""
    t = cls()
    if rng.random() < 0.1:
        return t  # unset bottom
    mode = rng.choice((1, 2, 3))  # MAX, LWW, AVG
    dim = rng.choice((1, 2, 3))

    def vec():
        vals = []
        for _ in range(dim):
            r = rng.random()
            if r < 0.08:
                vals.append(float("nan"))
            elif r < 0.16:
                vals.append(float("inf") if r < 0.12 else float("-inf"))
            else:
                vals.append(rng.uniform(-4.0, 4.0))
        return struct.pack(f"<{{dim}}f", *vals)

    if mode == 1:
        return cls.max_value(vec())
    if mode == 2:
        out = cls.lww(vec(), rng.randint(0, 4), rng.randint(1, 4))
        for _ in range(rng.randint(0, 2)):
            out.converge(cls.lww(vec(), rng.randint(0, 4), rng.randint(1, 4)))
        return out
    out = cls.avg(rng.randint(1, 4), rng.randint(0, 4), vec())
    for _ in range(rng.randint(0, 2)):
        out.converge(cls.avg(rng.randint(1, 4), rng.randint(0, 4), vec()))
    return out


def gen_bcount(rng, cls):
    """Arbitrary monotone-component states: the JOIN laws hold for any
    five pointwise-max components (the escrow-safety law below is what
    needs history-consistent inputs, and generates its own)."""
    b = cls()
    for d in (b.grants, b.incs, b.decs):
        for rid in rng.sample(range(1, 6), rng.randint(0, 3)):
            d[rid] = rng.randint(1, 1000)
    for m in (b.xi, b.xd):
        for _ in range(rng.randint(0, 3)):
            f, t = rng.randint(1, 5), rng.randint(1, 5)
            if f != t:
                m[(f, t)] = rng.randint(1, 100)
    return b


def _mk_gen_map(inner_name):
    """A MAP generator specialised to one registered inner lattice:
    random fields with random edit counters and tombstones over inner
    states drawn from the REGISTRY's own generator — plus an occasional
    cross-type field so the type-dominance rank is exercised."""
    def gen(rng, cls):
        from jylis_tpu.ops import compose
        m = cls()
        inner = compose.REGISTRY[inner_name]
        for field in (b"f1", b"f2", b"f3")[: rng.randint(0, 3)]:
            ver = {{
                rid: rng.randint(1, 4)
                for rid in rng.sample(range(1, 5), rng.randint(1, 2))
            }}
            tomb = (
                {{rid: rng.randint(0, 5) for rid in sorted(ver)}}
                if rng.random() < 0.4 else {{}}
            )
            m.converge_field(field, (inner_name, ver, tomb, inner.gen(rng)))
        if rng.random() < 0.25:
            other = rng.choice(sorted(compose.REGISTRY))
            m.converge_field(
                b"fx",
                (other, {{1: rng.randint(1, 3)}}, {{}},
                 compose.REGISTRY[other].gen(rng)),
            )
        return m
    return gen


LATTICES = [
{type_rows}
]

# the composed MAP, one row PER registered inner lattice: registering a
# new value type in ops/compose.REGISTRY auto-generates its composed
# join-law coverage here with no harness or manifest edit
from jylis_tpu.ops import compose as _compose  # noqa: E402

for _inner in sorted(_compose.REGISTRY):
    LATTICES.append(
        (f"MAP[{{_inner}}]", "jylis_tpu.ops.compose:MapCRDT",
         _mk_gen_map(_inner))
    )


@pytest.mark.parametrize("name,path,gen", LATTICES, ids=[t[0] for t in LATTICES])
def test_join_commutative(name, path, gen):
    cls = _lattice(path)
    for case in range(N_CASES):
        rng = random.Random(f"{{SEED}}:{{name}}:comm:{{case}}")
        a, b = gen(rng, cls), gen(rng, cls)
        assert _canon(_join(a, b)) == _canon(_join(b, a)), (name, case)


@pytest.mark.parametrize("name,path,gen", LATTICES, ids=[t[0] for t in LATTICES])
def test_join_associative(name, path, gen):
    cls = _lattice(path)
    for case in range(N_CASES):
        rng = random.Random(f"{{SEED}}:{{name}}:assoc:{{case}}")
        a, b, c = gen(rng, cls), gen(rng, cls), gen(rng, cls)
        left = _join(_join(a, b), c)
        right = _join(a, _join(b, c))
        assert _canon(left) == _canon(right), (name, case)


@pytest.mark.parametrize("name,path,gen", LATTICES, ids=[t[0] for t in LATTICES])
def test_join_idempotent(name, path, gen):
    cls = _lattice(path)
    for case in range(N_CASES):
        rng = random.Random(f"{{SEED}}:{{name}}:idem:{{case}}")
        a = gen(rng, cls)
        assert _canon(_join(a, a)) == _canon(a), (name, case)
        b = gen(rng, cls)
        ab = _join(a, b)
        assert _canon(_join(ab, b)) == _canon(ab), (name, case)


def test_bcount_escrow_safety():
    """The BCOUNT escrow-safety law (ops/bcount.py): replay random
    concurrent histories of grant/inc/dec/transfer over N replicas in
    which every spend passes only its replica's LOCAL rights check,
    deliver full-view states in arbitrary order, and require
    0 <= value <= bound on EVERY replica's view after EVERY step. This
    is the dynamic-law face of the invariant jmodel checks per explored
    protocol state (scripts/jmodel/world.py)."""
    from jylis_tpu.ops.bcount import BCount

    for case in range(N_CASES):
        rng = random.Random(f"{{SEED}}:BCOUNT:escrow:{{case}}")
        n = rng.randint(2, 4)
        states = [BCount() for _ in range(n)]
        for step in range(rng.randint(5, 30)):
            r = rng.randrange(n)
            st = states[r]
            roll = rng.random()
            if roll < 0.15:
                st.grant(r, rng.randint(1, 20))
            elif roll < 0.40:
                st.inc(r, rng.randint(1, 15))
            elif roll < 0.65:
                st.dec(r, rng.randint(1, 15))
            elif roll < 0.80:
                st.transfer(r, rng.randrange(n), rng.randint(1, 10),
                            rng.choice(("INC", "DEC")))
            else:
                # anti-entropy: some replica's full view converges into
                # another (any pair, any order — no causal delivery)
                states[rng.randrange(n)].converge(
                    copy.deepcopy(states[rng.randrange(n)])
                )
            for i, s in enumerate(states):
                v, bound = s.value(), s.bound()
                assert 0 <= v <= bound, (case, step, i, v, bound)
'''
