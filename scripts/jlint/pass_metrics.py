"""Pass 5 — metrics manifest parity (rules JL501/JL502).

The observability layer (jylis_tpu/obs/) works by NAME exactly like the
failpoints registry: ``registry.hist("journal.fsync")`` at the seam,
``SYSTEM LATENCY`` / the Prometheus endpoint to read it. A typo'd name
is a KeyError at runtime — but only on the path that typo'd it — and a
histogram/gauge/trace event added without documentation is invisible to
operators. Same cure as pass 4, same mechanics:

* every ``.hist(...)`` / ``.gauge_set(...)`` call in the product tree
  must use a STRING LITERAL name, every ``.trace_event(...)`` literal
  subsystem+event args, and every ``timed_drain("<TYPE>", ...)``
  decorator a literal type (its histogram is ``drain.<TYPE>``); each
  resulting name must appear in the committed
  ``scripts/jlint/metrics_manifest.json`` with a one-line description
  (JL501);
* every manifest entry must still have a call site and a
  non-placeholder description (JL502: stale / undescribed);
* every histogram/gauge name must be pre-registered in
  ``jylis_tpu/obs/__init__.py``'s SEAMS/GAUGES tuples (and every
  declared name used), so a scrape shows the full surface from boot and
  the declarations can't rot (JL501/JL502).

``python -m scripts.jlint --write-manifest`` regenerates the manifest,
preserving existing descriptions; new names get a placeholder that
fails JL502 until a human describes the metric. The CI metrics-smoke
step (scripts/metrics_smoke.py) reads the same manifest to assert every
histogram/gauge is actually present in a live node's scrape.

Manifest keys are ``<kind>:<name>`` with kind in {hist, gauge, trace};
trace names are ``<subsystem>.<event>``.
"""

from __future__ import annotations

import ast
import json
import os

from . import Finding, ROOT, iter_py_files
from .core import load_source

METRICS_MANIFEST_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "metrics_manifest.json"
)

OBS_INIT_REL = os.path.join("jylis_tpu", "obs", "__init__.py")

SCOPE = ("jylis_tpu",)

PLACEHOLDER = "(describe this metric)"

# attr-tail -> (kind, how many leading literal args form the name)
_CALL_KINDS = {
    "hist": ("hist", 1),
    "gauge_set": ("gauge", 1),
    "trace_event": ("trace", 2),
}


def _attr_tail(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _literal_strs(args: list[ast.expr], n: int) -> list[str] | None:
    if len(args) < n:
        return None
    out = []
    for a in args[:n]:
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            out.append(a.value)
        else:
            return None
    return out


def extract_sites(
    root: str = ROOT, scope: tuple[str, ...] = SCOPE
) -> tuple[dict[str, list[tuple[str, int]]], list[Finding]]:
    """{``kind:name``: [(rel path, line)]} for every literal-named
    metric call, plus JL501 findings for non-literal names."""
    sites: dict[str, list[tuple[str, int]]] = {}
    problems: list[Finding] = []
    for path in iter_py_files(root, scope):
        src = load_source(path, root)  # content-hash AST cache
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            tail = _attr_tail(node.func)
            if tail in _CALL_KINDS:
                kind, n = _CALL_KINDS[tail]
                # only method-style calls (obj.hist(...)): a bare
                # function named `hist` elsewhere is not the registry
                if not isinstance(node.func, ast.Attribute):
                    continue
                lits = _literal_strs(node.args, n)
                if lits is None:
                    problems.append(
                        Finding(
                            "JL501", src.rel, node.lineno,
                            f"{tail}() name must be {n} leading string "
                            "literal(s) — a computed metric name cannot "
                            "be audited against the manifest",
                            src.line_src(node.lineno),
                        )
                    )
                    continue
                name = f"{kind}:{'.'.join(lits)}"
                sites.setdefault(name, []).append((src.rel, node.lineno))
            elif tail == "timed_drain":
                lits = _literal_strs(node.args, 1)
                if lits is None:
                    problems.append(
                        Finding(
                            "JL501", src.rel, node.lineno,
                            "timed_drain() type must be a string literal "
                            "(it names the drain.<TYPE> histogram)",
                            src.line_src(node.lineno),
                        )
                    )
                    continue
                name = f"hist:drain.{lits[0]}"
                sites.setdefault(name, []).append((src.rel, node.lineno))
    return sites, problems


def declared_names(root: str = ROOT) -> tuple[set[str], set[str]]:
    """(SEAMS, GAUGES) parsed from jylis_tpu/obs/__init__.py by AST —
    jlint must not import the product package (jylis_tpu imports jax at
    import time)."""
    path = os.path.join(root, OBS_INIT_REL)
    seams: set[str] = set()
    gauges: set[str] = set()
    if not os.path.exists(path):
        return seams, gauges
    tree = ast.parse(open(path, encoding="utf-8").read())
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if not isinstance(tgt, ast.Name) or tgt.id not in ("SEAMS", "GAUGES"):
                continue
            if isinstance(node.value, (ast.Tuple, ast.List)):
                names = {
                    e.value
                    for e in node.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                }
                (seams if tgt.id == "SEAMS" else gauges).update(names)
    return seams, gauges


def load_manifest(path: str = METRICS_MANIFEST_PATH) -> dict[str, str]:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        return json.load(f).get("metrics", {})


def write_manifest(path: str = METRICS_MANIFEST_PATH) -> dict[str, str]:
    """Regenerate from the extracted call sites, preserving committed
    descriptions; new names get a placeholder JL502 rejects until a
    human replaces it."""
    sites, _ = extract_sites()
    existing = load_manifest(path)
    entries = {name: existing.get(name, PLACEHOLDER) for name in sorted(sites)}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(
            {
                "_comment": (
                    "Generated by `python -m scripts.jlint "
                    "--write-manifest` from .hist()/.gauge_set()/"
                    ".trace_event()/timed_drain() call sites under "
                    "jylis_tpu/. Keys are kind:name (hist/gauge/trace). "
                    "Descriptions are human-written and preserved across "
                    "regeneration; `make lint` fails on undeclared names "
                    "(JL501) and on stale or placeholder entries (JL502). "
                    "The CI metrics-smoke scrapes a live node and asserts "
                    "every hist/gauge entry here is present."
                ),
                "metrics": entries,
            },
            f, indent=2, sort_keys=True,
        )
        f.write("\n")
    return entries


def check(
    manifest_path: str = METRICS_MANIFEST_PATH,
    sites: dict[str, list[tuple[str, int]]] | None = None,
    pre_problems: list[Finding] | None = None,
    declared: tuple[set[str], set[str]] | None = None,
) -> list[Finding]:
    if sites is None:
        sites, pre_problems = extract_sites()
    out = list(pre_problems or [])
    rel = os.path.relpath(manifest_path, ROOT)
    manifest = load_manifest(manifest_path)
    if not manifest and sites:
        out.append(
            Finding(
                "JL502", rel, 1,
                "metrics manifest missing or empty — run `python -m "
                "scripts.jlint --write-manifest`, describe each metric, "
                "commit",
                "",
            )
        )
        return out
    for name in sorted(sites):
        if name not in manifest:
            where, line = sites[name][0]
            out.append(
                Finding(
                    "JL501", where, line,
                    f"metric `{name}` is not declared in {rel} — run "
                    "`python -m scripts.jlint --write-manifest` and "
                    "describe it",
                    name,
                )
            )
    for name, desc in sorted(manifest.items()):
        if name not in sites:
            out.append(
                Finding(
                    "JL502", rel, 1,
                    f"stale manifest entry `{name}`: no call site uses "
                    "it — delete the entry (--write-manifest "
                    "regenerates)",
                    name,
                )
            )
        elif not desc.strip() or desc.strip() == PLACEHOLDER:
            out.append(
                Finding(
                    "JL502", rel, 1,
                    f"metric `{name}` has no description — replace the "
                    "placeholder with one line saying what it measures",
                    name,
                )
            )
    # pre-registration parity: every used hist/gauge name must be in
    # obs.SEAMS/GAUGES (or it KeyErrors at runtime), and every declared
    # name must be used (or the scrape advertises a dead metric)
    seams, gauges = declared if declared is not None else declared_names()
    used_hists = {n[5:] for n in sites if n.startswith("hist:")}
    used_gauges = {n[6:] for n in sites if n.startswith("gauge:")}
    for name in sorted(used_hists - seams):
        where, line = sites[f"hist:{name}"][0]
        out.append(
            Finding(
                "JL501", where, line,
                f"histogram `{name}` is not pre-registered in "
                f"{OBS_INIT_REL} SEAMS (KeyError at runtime)",
                name,
            )
        )
    for name in sorted(used_gauges - gauges):
        where, line = sites[f"gauge:{name}"][0]
        out.append(
            Finding(
                "JL501", where, line,
                f"gauge `{name}` is not pre-registered in "
                f"{OBS_INIT_REL} GAUGES (KeyError at runtime)",
                name,
            )
        )
    for name in sorted(seams - used_hists):
        out.append(
            Finding(
                "JL502", OBS_INIT_REL, 1,
                f"SEAMS declares histogram `{name}` but no call site "
                "records into it — delete the declaration",
                name,
            )
        )
    for name in sorted(gauges - used_gauges):
        out.append(
            Finding(
                "JL502", OBS_INIT_REL, 1,
                f"GAUGES declares gauge `{name}` but no call site sets "
                "it — delete the declaration",
                name,
            )
        )
    return out
