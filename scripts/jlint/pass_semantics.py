"""Pass 11 — cross-language RESP semantic parity (JL1101/JL1102/JL1103).

Pass 3 proved both serving paths dispatch the same command *names*;
nothing checked that they agree on what those commands *mean*. This
pass extracts, for every natively-served command, the full argument
grammar (arity, strict/optional u64 args, validation predicates), the
RESP reply shapes, the error taxonomy, and the defer predicates — from
``native/serve_engine.cpp`` via the ``cpp_ast`` front-end (tokenizer +
recursive descent over the disciplined subset native/ uses, no
libclang) — and the same facts from the Python oracle's
``models/repo_*.py`` dispatch via the stdlib ``ast`` module with
one-level ``self._helper`` inlining. The two sides are diffed into a
committed manifest (``scripts/jlint/semantics_manifest.json``):

* ``commands``: per-command native/python grammar + mechanical
  ``divergences``; ``justified`` (hand-edited: divergence strings that
  are by-design) and ``note`` survive ``--write-manifest``;
* ``transport``: RESP parser limits (line/bulk/array) on both sides;
* ``thresholds``: drain thresholds that must match numerically across
  the seam (native constexpr vs Python module constants).

Reply shapes use one canonical vocabulary on both sides: ``"+OK"``,
``":u64"``, ``":i64"``, ``"$-1"``, ``"$bulk"``, ``"*0"``,
``"*2[$bulk,:u64]"``, ``"*n[*2[$bulk,:u64]]"``.

JL1101 fires on an unjustified grammar/bounds divergence (arity, u64
args, optional args, transport limits, thresholds); JL1102 on an
unjustified reply-shape or error-taxonomy divergence; JL1103 on
manifest drift, a stale ``justified`` entry, a placeholder note, a
natively-served command (per pass 3) the manifest does not cover, or a
stale generated fuzz harness (``tests/test_semantic_fuzz.py`` — see
``scripts/gen_semfuzz.py``). ``python -m scripts.jlint
--write-manifest`` regenerates the mechanical parts and the harness;
``--write-corpus`` re-records the fuzz corpus pinned to the manifest's
sha256, so a manifest edit without a re-record fails in tier-1.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re

from . import Finding, ROOT
from . import cpp_ast
from . import pass_parity

SERVE_ENGINE = os.path.join(ROOT, "native", "serve_engine.cpp")
RESP_PARSER = os.path.join(ROOT, "native", "resp_parser.cpp")
ENGINE_H = os.path.join(ROOT, "native", "engine.h")
MODELS_DIR = os.path.join(ROOT, "jylis_tpu", "models")
RESP_PY = os.path.join(ROOT, "jylis_tpu", "server", "resp.py")
SEMANTICS_MANIFEST_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "semantics_manifest.json"
)
HARNESS_PATH = os.path.join(ROOT, "tests", "test_semantic_fuzz.py")

PLACEHOLDER = "(explain this command's cross-language contract)"

# drain thresholds that must agree numerically across the language seam
# (native constexpr name, native unit, python module, python constant)
_THRESHOLDS = [
    ("TREG_PENDING_DRAIN", SERVE_ENGINE,
     os.path.join(MODELS_DIR, "repo_treg.py"), "PENDING_DRAIN_THRESHOLD"),
    ("ROW_DRAIN_THRESHOLD", ENGINE_H,
     os.path.join(MODELS_DIR, "tlog_table.py"), "ROW_DRAIN_THRESHOLD"),
    ("PENDING_DRAIN_THRESHOLD", ENGINE_H,
     os.path.join(MODELS_DIR, "tlog_table.py"), "PENDING_DRAIN_THRESHOLD"),
]


def manifest_sha(path: str = SEMANTICS_MANIFEST_PATH) -> str:
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


# ---- native extraction (cpp_ast) -------------------------------------------

_GUARD0 = re.compile(
    r'argc >= (\d+) && word_is \( buf , offs \[ 0 \] , lens \[ 0 \] , '
    r'"(\w+)" \)'
)
_GUARD1 = re.compile(
    r'argc >= (\d+) && word_is \( buf , offs \[ 1 \] , lens \[ 1 \] , '
    r'"(\w+)" \)'
)
_BOOL_GUARD = re.compile(
    r'bool is_(\w+) = argc >= (\d+) && word_is \( buf , offs \[ 1 \] , '
    r'lens \[ 1 \] , "(\w+)" \)'
)
_OFFS_IDX = re.compile(r"offs \[ (\d+) \]")

# source-literal spellings of the fixed reply fragments
_LIT_OK = '"+OK\\r\\n"'
_LIT_NULL = '"$-1\\r\\n"'
_LIT_ARR0 = '"*0\\r\\n"'
_LIT_ARR2 = '"*2\\r\\n$"'


def _iter_item_lists(block, depth=0):
    """Yield (token/group item list, loop depth) for every expression
    context in a statement tree."""
    for st in block.stmts:
        if isinstance(st, cpp_ast.ExprStmt):
            yield st.items, depth
        elif isinstance(st, cpp_ast.Return):
            yield st.value, depth
        elif isinstance(st, cpp_ast.If):
            yield st.cond, depth
            yield from _iter_item_lists(st.then, depth)
            if st.orelse is not None:
                yield from _iter_item_lists(st.orelse, depth)
        elif isinstance(st, cpp_ast.Loop):
            yield st.header, depth
            yield from _iter_item_lists(st.body, depth + 1)
        elif isinstance(st, cpp_ast.Block):
            yield from _iter_item_lists(st, depth)


def _block_text(block) -> str:
    return " ; ".join(
        cpp_ast.render(items) for items, _ in _iter_item_lists(block)
    )


def _native_replies(blocks, which_value=None) -> list[str]:
    """Canonical reply shapes emitted by a subcommand body."""
    lits: list[tuple[str, int]] = []
    fmts: list[str] = []
    data_memcpy = False
    for block in blocks:
        for items, depth in _iter_item_lists(block):
            for t in cpp_ast.flat_tokens(items):
                if t.kind == "str":
                    lits.append((t.text, depth))
            for g in cpp_ast.find_calls(items, "fmt_int_reply"):
                a = cpp_ast.split_args(g)
                if len(a) >= 3:
                    fmts.append(cpp_ast.render(a[2]))
            for g in cpp_ast.find_calls(items, "memcpy"):
                a = cpp_ast.split_args(g)
                if len(a) >= 2 and "-> data ( )" in cpp_ast.render(a[1]):
                    data_memcpy = True
    reps: set[str] = set()
    for text, _ in lits:
        if text == _LIT_OK:
            reps.add("+OK")
        elif text == _LIT_NULL:
            reps.add("$-1")
        elif text == _LIT_ARR0:
            reps.add("*0")
    comp = [d for text, d in lits if text == _LIT_ARR2]
    if comp:
        # the pair-array composite swallows its own $bulk/:u64 parts
        if any(d > 0 for d in comp):
            reps.add("*n[*2[$bulk,:u64]]")
        if any(d == 0 for d in comp):
            reps.add("*2[$bulk,:u64]")
    else:
        if data_memcpy:
            reps.add("$bulk")  # memoised oracle-rendered bulk reply
        for signed in fmts:
            if signed == "true":
                reps.add(":i64")
            elif signed == "false":
                reps.add(":u64")
            else:  # `which == 1`: signed exactly for PNCOUNT
                reps.add(":i64" if which_value == 1 else ":u64")
    return sorted(reps)


def _native_args(blocks) -> tuple[list[int], list[int]]:
    """(strict u64 client-arg indexes, optional u64 client-arg indexes)
    from the `parse_amount` guards: a failed strict parse defers to the
    oracle's help path; a failed optional parse means "all"."""
    u64: set[int] = set()
    opt: set[int] = set()
    for block in blocks:
        for st in cpp_ast.walk(block):
            if not isinstance(st, cpp_ast.If):
                continue
            calls = list(cpp_ast.find_calls(st.cond, "parse_amount"))
            if not calls:
                continue
            a = cpp_ast.split_args(calls[0])
            m = _OFFS_IDX.search(cpp_ast.render(a[0])) if a else None
            if m is None:
                continue
            idx = int(m.group(1))
            then_txt = _block_text(st.then)
            if "UINT64_MAX" in then_txt and "defer ( )" not in then_txt:
                opt.add(idx)
            else:
                u64.add(idx)
    return sorted(u64), sorted(opt)


def _native_defers(blocks) -> list[str]:
    """Rendered guard conditions of every `return defer()` — the exact
    predicates under which the engine bounces to the oracle."""
    out: list[str] = []

    def rec(block, conds):
        for st in block.stmts:
            if isinstance(st, cpp_ast.Return):
                if cpp_ast.render(st.value) == "defer ( )":
                    out.append(" && ".join(conds) if conds else "fallthrough")
            elif isinstance(st, cpp_ast.If):
                c = cpp_ast.render(st.cond)
                rec(st.then, conds + [c])
                if st.orelse is not None:
                    rec(st.orelse, conds + [f"! ( {c} )"])
            elif isinstance(st, cpp_ast.Loop):
                rec(st.body, conds)
            elif isinstance(st, cpp_ast.Block):
                rec(st, conds)

    for b in blocks:
        rec(b, [])
    seen: set[str] = set()
    uniq = []
    for d in out:
        if d not in seen:
            seen.add(d)
            uniq.append(d)
    return uniq


def _native_error_mode(blocks) -> str:
    for block in blocks:
        for items, _ in _iter_item_lists(block):
            for t in cpp_ast.flat_tokens(items):
                if t.kind == "str" and t.text.startswith('"-'):
                    return "inline-error"
    return "defer"


def _native_grammar(min_argc, blocks, which_value=None,
                    validators=None) -> dict:
    u64, opt = _native_args(blocks)
    return {
        "min_argc": min_argc,
        "u64_args": u64,
        "opt_u64_args": opt,
        "validators": validators or [],
        "replies": _native_replies(blocks, which_value),
        "error_mode": _native_error_mode(blocks),
        "defers": _native_defers(blocks),
    }


def _extract_counter_block(block, which_types, out) -> None:
    polarity_body = None
    guards = []  # (sub, min_argc, restrict_to_which, then_block)
    for st in block.stmts:
        if not isinstance(st, cpp_ast.If):
            continue
        cond = cpp_ast.render(st.cond)
        m = _GUARD1.search(cond)
        if m:
            then_txt = _block_text(st.then)
            pm = re.search(r"polarity = (\d+)", then_txt)
            restrict = 1 if "which == 1" in cond else None
            guards.append(
                (m.group(2), int(m.group(1)), restrict, st.then, pm is not None)
            )
        elif cond == "polarity >= 0":
            polarity_body = st.then
    for sub, min_argc, restrict, then, is_polarity in guards:
        blocks = [polarity_body] if is_polarity and polarity_body else [then]
        for wv, tname in sorted(which_types.items()):
            if restrict is not None and wv != restrict:
                continue
            out[f"{tname} {sub}"] = _native_grammar(min_argc, blocks, wv)


def _extract_ujson_block(tname, block, out) -> None:
    shared = []  # the write-path statements after the bool guards
    flags: dict[str, tuple[str, int]] = {}  # is_<x> suffix -> (SUB, argc)
    for st in block.stmts:
        if isinstance(st, cpp_ast.If):
            m = _GUARD1.search(cpp_ast.render(st.cond))
            if m:
                out[f"{tname} {m.group(2)}"] = _native_grammar(
                    int(m.group(1)), [st.then]
                )
                continue
        if isinstance(st, cpp_ast.ExprStmt):
            m = _BOOL_GUARD.search(cpp_ast.render(st.items))
            if m:
                flags[m.group(1)] = (m.group(3), int(m.group(2)))
                continue
        shared.append(st)
    if not flags:
        return
    shared_block = cpp_ast.Block(shared)
    # per-sub value validators from the flag-guarded ok assignments
    validators: dict[str, list] = {sub: [] for sub, _ in flags.values()}
    for st in cpp_ast.walk(shared_block):
        if not isinstance(st, cpp_ast.If):
            continue
        cond = cpp_ast.render(st.cond)
        then_txt = _block_text(st.then)
        for suffix, (sub, _) in flags.items():
            if f"is_{suffix}" not in cond:
                continue
            for check in ("ujson_prim_ok", "ujson_doc_ok"):
                if check in then_txt:
                    validators[sub].append({"arg": "last", "check": check})
    if "utf8_valid" in _block_text(shared_block):
        for sub in validators:
            validators[sub].append({"arg": "path", "check": "utf8_valid"})
    for sub, min_argc in flags.values():
        out[f"{tname} {sub}"] = _native_grammar(
            min_argc, [shared_block], validators=validators[sub]
        )


def extract_native(path: str = SERVE_ENGINE) -> dict[str, dict]:
    """{"TYPE SUB": grammar} from the engine's dispatch statement tree."""
    unit = cpp_ast.parse_file(path)
    fn = unit.functions["jy_eng_scan_apply2"]
    loop = [s for s in fn.body.stmts if isinstance(s, cpp_ast.Loop)][-1]
    which_types: dict[int, str] = {}
    out: dict[str, dict] = {}
    for st in loop.body.stmts:
        if not isinstance(st, cpp_ast.If):
            continue
        cond = cpp_ast.render(st.cond)
        m = _GUARD0.search(cond)
        if m:
            then_txt = _block_text(st.then)
            wm = re.fullmatch(r"which = (\d+)", then_txt)
            if wm:
                which_types[int(wm.group(1))] = m.group(2)
            elif m.group(2) == "UJSON":
                _extract_ujson_block(m.group(2), st.then, out)
            else:
                inner: dict[str, dict] = {}
                for sst in st.then.stmts:
                    if not isinstance(sst, cpp_ast.If):
                        continue
                    sm = _GUARD1.search(cpp_ast.render(sst.cond))
                    if sm:
                        inner[f"{m.group(2)} {sm.group(2)}"] = _native_grammar(
                            int(sm.group(1)), [sst.then]
                        )
                out.update(inner)
        elif cond == "which >= 0":
            _extract_counter_block(st.then, which_types, out)
    return out


# ---- python extraction (stdlib ast + one-level helper inlining) ------------


def _fold_int(node):
    """Constant-fold an int expression (literals and + - * //)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, ast.BinOp):
        left, right = _fold_int(node.left), _fold_int(node.right)
        if left is None or right is None:
            return None
        if isinstance(node.op, ast.Mult):
            return left * right
        if isinstance(node.op, ast.Add):
            return left + right
        if isinstance(node.op, ast.Sub):
            return left - right
        if isinstance(node.op, ast.FloorDiv):
            return left // right
    return None


def _is_name_call(node, name):
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id == name
    )


def _self_methods(nodes) -> set[str]:
    """Every self.<method> referenced anywhere under the given nodes."""
    found: set[str] = set()
    for root in nodes:
        for n in ast.walk(root):
            if (
                isinstance(n, ast.Attribute)
                and isinstance(n.value, ast.Name)
                and n.value.id == "self"
            ):
                found.add(n.attr)
    return found


def _py_facts(stmts, resolve) -> dict:
    """Argument-grammar facts from a dispatch branch: need()/parse_u64
    indexes on the literal name `args`, `len(args) < N` raises, and
    ValueError->ParseError value validation — helpers resolved through
    `resolve` are scanned too (transitively, cycle-safe)."""
    bodies: list = list(stmts)
    seen_methods: set[str] = set()
    frontier = _self_methods(bodies)
    while frontier:
        nxt: set[str] = set()
        for m in frontier:
            if m in seen_methods:
                continue
            seen_methods.add(m)
            fn = resolve(m)
            if fn is not None:
                bodies.extend(fn.body)
                nxt |= _self_methods(fn.body)
        frontier = nxt - seen_methods
    needs: set[int] = set()
    u64: set[int] = set()
    opt: set[int] = set()
    len_min = 0
    value_parse = False
    raises = False
    for root in bodies:
        for n in ast.walk(root):
            if isinstance(n, ast.Call):
                args_first = (
                    n.args
                    and isinstance(n.args[0], ast.Name)
                    and n.args[0].id == "args"
                )
                if _is_name_call(n, "need") and args_first and len(n.args) == 2:
                    idx = _fold_int(n.args[1])
                    if idx is not None:
                        needs.add(idx)
                if (
                    _is_name_call(n, "parse_opt_count")
                    and args_first
                    and len(n.args) == 2
                ):
                    idx = _fold_int(n.args[1])
                    if idx is not None:
                        opt.add(idx)
                if _is_name_call(n, "parse_u64") and n.args:
                    a = n.args[0]
                    idx = None
                    if _is_name_call(a, "need") and len(a.args) == 2:
                        idx = _fold_int(a.args[1])
                    elif (
                        isinstance(a, ast.Subscript)
                        and isinstance(a.value, ast.Name)
                        and a.value.id == "args"
                    ):
                        idx = _fold_int(a.slice)
                    if idx is not None:
                        u64.add(idx)
            if isinstance(n, ast.Compare) and len(n.ops) == 1:
                left = n.left
                if (
                    isinstance(n.ops[0], ast.Lt)
                    and _is_name_call(left, "len")
                    and left.args
                    and isinstance(left.args[0], ast.Name)
                    and left.args[0].id == "args"
                ):
                    bound = _fold_int(n.comparators[0])
                    if bound is not None:
                        len_min = max(len_min, bound)
            if isinstance(n, ast.Try):
                catches_value_error = any(
                    h.type is not None and "ValueError" in ast.dump(h.type)
                    for h in n.handlers
                )
                reraises = any(
                    isinstance(x, ast.Raise)
                    for h in n.handlers
                    for x in ast.walk(h)
                )
                if catches_value_error and reraises:
                    value_parse = True
            if isinstance(n, ast.Raise):
                raises = True
    min_py = len_min
    if needs:
        min_py = max(min_py, max(needs) + 1)
    if u64:
        min_py = max(min_py, max(u64) + 1)
    validators = []
    if value_parse:
        validators.append({"arg": "last", "check": "value_parse"})
    return {
        # oracle `args` excludes the type word: client argc = len + 1
        "min_argc": min_py + 1,
        "u64_args": sorted(i + 1 for i in u64),
        "opt_u64_args": sorted(i + 1 for i in opt),
        "validators": validators,
        "errors": (
            ["ParseError -> datatype help"]
            if (raises or needs or u64)
            else []
        ),
    }


def _resp_event(call) -> str | None:
    """Canonical reply event for a `resp.<method>(...)` call."""
    if not (
        isinstance(call, ast.Call)
        and isinstance(call.func, ast.Attribute)
        and isinstance(call.func.value, ast.Name)
        and call.func.value.id == "resp"
    ):
        return None
    m = call.func.attr
    if m == "ok":
        return "+OK"
    if m == "u64":
        return ":u64"
    if m == "i64":
        return ":i64"
    if m == "string":
        return "$bulk"
    if m == "null":
        return "$-1"
    if m == "array_start":
        n = _fold_int(call.args[0]) if call.args else None
        if n == 0:
            return "*0"
        if n is None:
            return "*n["
        return f"*{n}["
    return None


def _alts_stmts(stmts, resolve, visited) -> set:
    alts = {((), False)}
    for s in stmts:
        new = set()
        for ev, done in alts:
            if done:
                new.add((ev, done))
                continue
            for ev2, done2 in _alts_stmt(s, resolve, visited):
                new.add((ev + ev2, done2))
        alts = new
    return alts


def _alts_stmt(s, resolve, visited) -> set:
    if isinstance(s, (ast.Return, ast.Raise)):
        return {((), True)}
    if isinstance(s, ast.If):
        return _alts_stmts(s.body, resolve, visited) | _alts_stmts(
            s.orelse, resolve, visited
        )
    if isinstance(s, (ast.For, ast.While)):
        inner = _alts_stmts(s.body, resolve, visited)
        outs = set()
        for ev, _ in inner:
            outs.add(((("loop", ev),), False) if ev else ((), False))
        return outs or {((), False)}
    if isinstance(s, ast.Try):
        outs = _alts_stmts(s.body, resolve, visited)
        for h in s.handlers:
            outs |= _alts_stmts(h.body, resolve, visited)
        return outs
    if isinstance(s, ast.Expr) and isinstance(s.value, ast.Call):
        ev = _resp_event(s.value)
        if ev is not None:
            return {((ev,), False)}
        call = s.value
        if (
            isinstance(call.func, ast.Attribute)
            and isinstance(call.func.value, ast.Name)
            and call.func.value.id == "self"
            and any(
                isinstance(a, ast.Name) and a.id == "resp" for a in call.args
            )
            and call.func.attr not in visited
        ):
            fn = resolve(call.func.attr)
            if fn is not None:
                return _alts_stmts(
                    fn.body, resolve, visited | {call.func.attr}
                )
    return {((), False)}


def _canon_events(events) -> list[str]:
    out: list[str] = []
    i = 0
    while i < len(events):
        e = events[i]
        if isinstance(e, tuple) and e and e[0] == "loop":
            out.append("loop(" + ",".join(_canon_events(e[1])) + ")")
            i += 1
            continue
        if isinstance(e, str) and e.startswith("*") and e.endswith("["):
            hdr = e[1:-1]
            i += 1
            if hdr == "n":
                if (
                    i < len(events)
                    and isinstance(events[i], tuple)
                    and events[i][0] == "loop"
                ):
                    inner = _canon_events(events[i][1])
                    i += 1
                else:
                    inner = []
                out.append("*n[" + ",".join(inner) + "]")
            else:
                k = int(hdr)
                elems: list[str] = []
                while len(elems) < k and i < len(events):
                    elems.extend(_canon_events([events[i]]))
                    i += 1
                out.append(f"*{k}[" + ",".join(elems) + "]")
            continue
        out.append(e)
        i += 1
    return out


def _py_replies(stmts, resolve) -> list[str]:
    shapes: set[str] = set()
    for ev, _ in _alts_stmts(stmts, resolve, set()):
        if not ev:
            continue  # pure-error path: no reply events
        shapes.add("+".join(_canon_events(list(ev))))
    return sorted(shapes)


def extract_python(models_dir: str = MODELS_DIR) -> dict[str, dict]:
    """{"TYPE SUB": grammar} from every repo class's `apply` dispatch."""
    out: dict[str, dict] = {}
    for fname in sorted(os.listdir(models_dir)):
        if not (fname.startswith("repo_") and fname.endswith(".py")):
            continue
        path = os.path.join(models_dir, fname)
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
        classes = {
            c.name: c for c in tree.body if isinstance(c, ast.ClassDef)
        }
        methods = {
            cname: {
                m.name: m
                for m in c.body
                if isinstance(m, ast.FunctionDef)
            }
            for cname, c in classes.items()
        }

        def make_resolver(cname):
            def resolve(mname):
                cur = cname
                while cur is not None:
                    if mname in methods.get(cur, {}):
                        return methods[cur][mname]
                    bases = [
                        b.id
                        for b in classes[cur].bases
                        if isinstance(b, ast.Name) and b.id in classes
                    ]
                    cur = bases[0] if bases else None
                return None

            return resolve

        for cname, cls in classes.items():
            tname = None
            for stmt in cls.body:
                if (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "name"
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, str)
                ):
                    tname = stmt.value.value
            resolve = make_resolver(cname)
            apply_fn = methods.get(cname, {}).get("apply")
            if tname is None or apply_fn is None:
                continue
            for st in apply_fn.body:
                # a dispatch branch is a top-level `if` whose test is a
                # BARE compare of `op` against bytes constants (guards
                # like `op in (...) and len(args) >= 2` are preludes)
                if not (isinstance(st, ast.If) and isinstance(st.test, ast.Compare)):
                    continue
                operands = [st.test.left] + list(st.test.comparators)
                flat: list[ast.expr] = []
                for o in operands:
                    if isinstance(o, (ast.Tuple, ast.List, ast.Set)):
                        flat.extend(o.elts)
                    else:
                        flat.append(o)
                if not any(
                    isinstance(o, ast.Name) and o.id == "op" for o in flat
                ):
                    continue
                subs = [
                    o.value.decode("ascii", "replace")
                    for o in flat
                    if isinstance(o, ast.Constant)
                    and isinstance(o.value, bytes)
                ]
                subs = [s for s in subs if s.isupper() and s.isalpha()]
                if not subs:
                    continue
                rec = _py_facts(st.body, resolve)
                rec["replies"] = _py_replies(st.body, resolve)
                for sub in subs:
                    out[f"{tname} {sub}"] = rec
    return out


# ---- transport + thresholds ------------------------------------------------


def _eval_cpp_int(text: str):
    total = 1
    for part in text.split("*"):
        digits = re.sub(r"[A-Za-z']", "", part).strip()
        if not digits.isdigit():
            return None
        total *= int(digits)
    return total


def extract_transport() -> dict:
    unit = cpp_ast.parse_file(RESP_PARSER)
    native = {
        name: _eval_cpp_int(unit.constants.get(name, ""))
        for name in ("MAX_LINE", "MAX_BULK", "MAX_ARRAY")
    }
    with open(RESP_PY, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=RESP_PY)
    bulk = None
    guards: set[int] = set()
    for n in ast.walk(tree):
        if (
            isinstance(n, ast.Assign)
            and len(n.targets) == 1
            and isinstance(n.targets[0], ast.Name)
            and n.targets[0].id == "_MAX_BULK"
        ):
            bulk = _fold_int(n.value)
        if isinstance(n, ast.Compare) and len(n.ops) == 1 and isinstance(
            n.ops[0], ast.Gt
        ):
            v = _fold_int(n.comparators[0])
            if v is not None and v > 1:
                guards.add(v)
    guards.discard(bulk)
    python = {
        "MAX_LINE": min(guards) if guards else None,
        "MAX_BULK": bulk,
        "MAX_ARRAY": max(guards) if guards else None,
    }
    divergences = [
        f"transport: native {name}={native[name]} != oracle {python[name]}"
        for name in ("MAX_LINE", "MAX_BULK", "MAX_ARRAY")
        if native[name] != python[name]
    ]
    return {"native": native, "python": python, "divergences": divergences}


def extract_thresholds() -> dict:
    units: dict[str, cpp_ast.Unit] = {}
    py_consts: dict[str, dict[str, int]] = {}
    out: dict[str, dict] = {}
    for cname, cpath, ppath, pname in _THRESHOLDS:
        if cpath not in units:
            units[cpath] = cpp_ast.parse_file(cpath)
        if ppath not in py_consts:
            with open(ppath, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=ppath)
            consts: dict[str, int] = {}
            for n in ast.walk(tree):
                if (
                    isinstance(n, ast.Assign)
                    and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                ):
                    v = _fold_int(n.value)
                    if v is not None:
                        consts[n.targets[0].id] = v
            py_consts[ppath] = consts
        native = _eval_cpp_int(units[cpath].constants.get(cname, ""))
        python = py_consts[ppath].get(pname)
        rec = {"native": native, "python": python, "divergences": []}
        if native != python:
            rec["divergences"] = [
                f"threshold: native {cname}={native} != oracle "
                f"{pname}={python}"
            ]
        out[cname] = rec
    return out


# ---- manifest --------------------------------------------------------------


def _diff(native: dict, python: dict) -> list[str]:
    out: list[str] = []
    if native["min_argc"] != python["min_argc"]:
        out.append(
            f"arity: native min_argc {native['min_argc']} != oracle "
            f"{python['min_argc']}"
        )
    if native["u64_args"] != python["u64_args"]:
        out.append(
            f"u64-args: native {native['u64_args']} != oracle "
            f"{python['u64_args']}"
        )
    if native["opt_u64_args"] != python["opt_u64_args"]:
        out.append(
            f"opt-u64-args: native {native['opt_u64_args']} != oracle "
            f"{python['opt_u64_args']}"
        )
    if native["replies"] != python["replies"]:
        out.append(
            f"replies: native {native['replies']} != oracle "
            f"{python['replies']}"
        )
    if native["error_mode"] != "defer":
        out.append(
            "errors: native emits inline error replies; the oracle's "
            "ParseError help path is the only error surface"
        )
    return out


def _load_committed(path: str = SEMANTICS_MANIFEST_PATH) -> dict:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def build_manifest(old: dict | None = None) -> dict:
    if old is None:
        old = _load_committed()
    native = extract_native()
    python = extract_python()
    old_cmds = old.get("commands", {})
    commands: dict[str, dict] = {}
    for key in sorted(native):
        nat = native[key]
        py = python.get(key)
        divergences = (
            _diff(nat, py)
            if py is not None
            else ["oracle-missing: no Python dispatch path extracted"]
        )
        commands[key] = {
            "native": nat,
            "python": py,
            "divergences": divergences,
            "justified": old_cmds.get(key, {}).get("justified", []),
            "note": old_cmds.get(key, {}).get("note", PLACEHOLDER),
        }
    return {
        "_comment": (
            "Generated by `python -m scripts.jlint --write-manifest` from "
            "native/serve_engine.cpp (via scripts/jlint/cpp_ast.py), "
            "native/resp_parser.cpp, native/engine.h and "
            "jylis_tpu/models/repo_*.py. Grammar, replies, divergences, "
            "transport and thresholds are mechanical — do not edit; "
            "`justified` and `note` are human-written and preserved. "
            "`make lint` fails on drift or placeholder notes (JL1103), "
            "unjustified grammar/bounds divergence (JL1101), and "
            "unjustified reply-shape/error divergence (JL1102). After any "
            "change, re-record the fuzz corpus with --write-corpus."
        ),
        "commands": commands,
        "transport": extract_transport(),
        "thresholds": extract_thresholds(),
    }


def write_manifest(path: str = SEMANTICS_MANIFEST_PATH) -> dict:
    manifest = build_manifest()
    with open(path, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
        f.write("\n")
    from .. import gen_semfuzz

    with open(HARNESS_PATH, "w", encoding="utf-8") as f:
        f.write(gen_semfuzz.render_harness(manifest))
    return manifest


# ---- check -----------------------------------------------------------------


def check(
    manifest_path: str = SEMANTICS_MANIFEST_PATH,
    harness_path: str = HARNESS_PATH,
) -> list[Finding]:
    out: list[Finding] = []
    rel = os.path.relpath(manifest_path, ROOT)
    committed = _load_committed(manifest_path)
    if not committed:
        out.append(
            Finding(
                "JL1103", rel, 1,
                "semantics manifest missing/unreadable — run `python -m "
                "scripts.jlint --write-manifest` and commit it",
                "",
            )
        )
        return out
    current = build_manifest(committed)
    cur_cmds = current["commands"]
    com_cmds = committed.get("commands", {})

    for key, rec in cur_cmds.items():
        crec = com_cmds.get(key)
        if crec is None:
            out.append(
                Finding(
                    "JL1103", rel, 1,
                    f"`{key}` is served natively but absent from the "
                    "semantics manifest — run --write-manifest, describe "
                    "the contract, commit",
                    key,
                )
            )
            continue
        for fieldname in ("native", "python", "divergences"):
            if crec.get(fieldname) != rec[fieldname]:
                out.append(
                    Finding(
                        "JL1103", rel, 1,
                        f"semantics manifest drift: `{key}` / "
                        f"`{fieldname}` committed "
                        f"{json.dumps(crec.get(fieldname), sort_keys=True)} "
                        f"!= extracted "
                        f"{json.dumps(rec[fieldname], sort_keys=True)} — "
                        "run --write-manifest, review, commit",
                        key,
                    )
                )
        justified = crec.get("justified", [])
        for j in justified:
            if j not in rec["divergences"]:
                out.append(
                    Finding(
                        "JL1103", rel, 1,
                        f"stale justification on `{key}`: "
                        f"{json.dumps(j)} no longer matches any extracted "
                        "divergence — delete it",
                        key,
                    )
                )
        note = crec.get("note", "")
        if not str(note).strip() or note == PLACEHOLDER:
            out.append(
                Finding(
                    "JL1103", rel, 1,
                    f"`{key}` has no note — one line on the cross-language "
                    "contract (what the engine serves, when it defers)",
                    key,
                )
            )
        for d in rec["divergences"]:
            if d in justified:
                continue
            rule = (
                "JL1102"
                if d.startswith(("replies", "errors", "oracle-missing"))
                else "JL1101"
            )
            out.append(
                Finding(
                    rule, "native/serve_engine.cpp", 1,
                    f"`{key}` diverges from the oracle: {d} — fix the "
                    "divergence (with a pinning test) or add the exact "
                    "string to the manifest's `justified` list with a note",
                    key,
                )
            )
    for key in com_cmds:
        if key not in cur_cmds:
            out.append(
                Finding(
                    "JL1103", rel, 1,
                    f"manifest entry `{key}` no longer matches any "
                    "natively-served command — run --write-manifest",
                    key,
                )
            )

    for section in ("transport", "thresholds"):
        if committed.get(section) != current[section]:
            out.append(
                Finding(
                    "JL1103", rel, 1,
                    f"semantics manifest drift in `{section}` — run "
                    "--write-manifest, review, commit",
                    section,
                )
            )
    for d in current["transport"]["divergences"]:
        out.append(
            Finding("JL1101", "native/resp_parser.cpp", 1,
                    f"{d} — the parsers must reject identical inputs", d)
        )
    for name, rec in current["thresholds"].items():
        for d in rec["divergences"]:
            out.append(
                Finding(
                    "JL1101", "native/serve_engine.cpp", 1,
                    f"{d} — the native defer predicate and the oracle "
                    "drain predicate must agree",
                    d,
                )
            )

    # coverage: every pass-3 native command must have a manifest entry
    for t, subs in pass_parity.extract_native().items():
        for sub in subs:
            if f"{t} {sub}" not in cur_cmds:
                out.append(
                    Finding(
                        "JL1103", rel, 1,
                        f"`{t} {sub}` is dispatched natively (pass 3) but "
                        "the semantic extractor produced no entry — "
                        "cpp_ast extraction is incomplete",
                        f"{t} {sub}",
                    )
                )

    # generated differential-fuzz harness must match a fresh render
    from .. import gen_semfuzz

    hrel = os.path.relpath(harness_path, ROOT)
    try:
        with open(harness_path, encoding="utf-8") as f:
            committed_harness = f.read()
    except OSError:
        committed_harness = None
    if committed_harness != gen_semfuzz.render_harness(current):
        out.append(
            Finding(
                "JL1103", hrel, 1,
                "generated semantic-fuzz harness is stale or missing — "
                "run `python -m scripts.jlint --write-manifest` and commit "
                "the regenerated file",
                "",
            )
        )
    return out
