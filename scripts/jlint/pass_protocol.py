"""Pass 10 — protocol atlas (rules JL1001/JL1002/JL1003).

The cluster protocol is ~6 message kinds × an active/passive role split
× a per-address dial state machine × the sync-serve machinery — and the
lane bus/bridge rides the same engine. Until this pass its full
transition relation lived only in the heads of whoever last read
``cluster.py``; the drill matrix samples behaviours, it does not pin
them. This pass extracts, statically, what every handler is PERMITTED
to do and commits it as ``scripts/jlint/protocol_manifest.json`` — the
atlas jmodel (scripts/jmodel) explores and the next protocol rewrite
(digest-driven delta intervals) diffs itself against.

What is extracted, per (section, key):

* ``role:active`` / ``role:passive`` — one entry per ``isinstance(msg,
  X)`` branch of ``_active_msg`` / ``_passive_msg`` plus the
  ``<fallthrough>`` tail, mapping the branch to its canonical *effect
  tokens*: sends by message type (``send:MsgPong``), broadcasts,
  converge calls (``converge:data`` / ``converge:addrs``), state
  mutations (``set:``/``mut:``), teardown reasons (``drop:UNEXPECTED``),
  declared message drops (``msg_drop:pong_unsolicited``), metric /
  trace / histogram / gauge emissions, task spawns and failpoints.
* ``handshake`` — the pre-established state, split per role (the
  ``if active:`` branches of ``_handshake``).
* ``sync`` — the request/serve machinery (``_maybe_request_sync``,
  ``_request_sync``, ``_serve_syncs``, ``_data_frames``,
  ``_system_frames``, ``_stream_sync``, ``_send_frame``).
* ``dial`` — the per-address dial state machine (``_heartbeat``,
  ``_sync_actives``, ``_dial``, ``_active_missed``,
  ``_inbound_contact``, ``_drop``).
* ``send`` — the broadcast/held-queue path (``broadcast_deltas``,
  ``_flush_held``, ``_send_to_actives``, ``_send``, ``_broadcast_msg``).
* ``recv`` — the message pump (``_accept``, ``_read_loop``): framing,
  CRC and codec teardown reasons, the pre-handshake gate.

Rules:

* **JL1001** — a handler produces an effect the committed manifest does
  not declare (or a whole branch/section the manifest lacks): new
  behaviour entered the protocol unreviewed.
* **JL1002** — an undeclared fall-through: a message type from msg.py
  with no ``isinstance`` branch in a role handler whose
  ``<fallthrough>`` tail is effect-free, or any branch whose effect set
  is EMPTY — a silent ignore. Every ignore must be a declared drop
  (``Cluster._drop_msg``: counted + traced) with a reason.
* **JL1003** — manifest drift the other way: declared effects no
  handler produces any more, stale entries, a missing manifest, or a
  missing/placeholder note. ``python -m scripts.jlint --write-manifest``
  regenerates the effect sets, preserving the human-written notes.
"""

from __future__ import annotations

import ast
import json
import os

from . import Finding, ROOT, dotted_name
from .core import load_source

PROTOCOL_MANIFEST_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "protocol_manifest.json"
)

CLUSTER_REL = os.path.join("jylis_tpu", "cluster", "cluster.py")
MSG_REL = os.path.join("jylis_tpu", "cluster", "msg.py")

PLACEHOLDER = "(describe this transition)"

HANDLERS = {"role:active": "_active_msg", "role:passive": "_passive_msg"}
SYNC_FUNCS = (
    "_maybe_request_sync", "_request_sync", "_serve_syncs",
    "_chunk_frames", "_data_frames", "_range_frames", "_serve_ranges",
    "_handle_tree", "_continue_ranges", "_force_range_repair",
    "_track_seq", "_system_frames", "_stream_sync", "_send_frame",
)
DIAL_FUNCS = (
    "_heartbeat", "_sync_actives", "_dial", "_active_missed",
    "_inbound_contact", "_drop",
)
RECV_FUNCS = ("_accept", "_read_loop")
SEND_FUNCS = (
    "broadcast_deltas", "_log_delta", "_retransmit_unacked",
    "_send_reset", "_flush_held", "_send_to_actives", "_send",
    "_broadcast_msg",
)

# query-only helpers whose calls are not effects (they mutate nothing
# and send nothing); everything else a handler calls on self is recorded
_PURE_HELPERS = frozenset(
    {
        "_wire", "_conn_desc", "_peer_key", "_backoff_ticks",
        "_worth_holding", "_worst_lag_ms", "_backlog_ms", "lag_snapshot",
        "metrics_totals",
    }
)

# receiver-method calls that mutate protocol state when the receiver is
# rooted at self/conn (deque/list/set/dict mutators + close/cancel)
_MUTATORS = frozenset(
    {
        "append", "appendleft", "pop", "popleft", "add", "discard",
        "remove", "clear", "extend", "update", "close", "cancel",
    }
)


# ---- effect extraction ------------------------------------------------------


def _rooted(dotted: str) -> bool:
    return dotted.startswith("self.") or dotted.startswith("conn.")


def _msg_ctor(node: ast.AST) -> str | None:
    """`MsgPong()` / `MsgSyncDone()` argument -> the message class name."""
    if isinstance(node, ast.Call):
        name = dotted_name(node.func).split(".")[-1]
        if name.startswith("Msg"):
            return name
    return None


def _const_attr(node: ast.AST, owner: str) -> str | None:
    """`Drop.IDLE` / `MsgDrop.PONG_UNMATCHED` -> the constant name."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == owner
    ):
        return node.attr
    return None


def _classify_call(call: ast.Call) -> str | None:
    name = dotted_name(call.func)
    if name == "self._send" and len(call.args) >= 2:
        ctor = _msg_ctor(call.args[1])
        return f"send:{ctor or '?'}"
    if name == "self._broadcast_msg" and call.args:
        ctor = _msg_ctor(call.args[0])
        return f"broadcast:{ctor or '?'}"
    if name == "self._send_to_actives":
        return "broadcast:frame"
    if name.endswith(".send_raw"):
        return "send:raw"
    if name == "self._drop":
        reason = "EOF"
        if len(call.args) >= 2:
            reason = _const_attr(call.args[1], "Drop") or "?"
        for kw in call.keywords:
            if kw.arg == "reason":
                reason = _const_attr(kw.value, "Drop") or "?"
        return f"drop:{reason}"
    if name == "self._drop_msg" and len(call.args) >= 2:
        const = _const_attr(call.args[1], "MsgDrop")
        return f"msg_drop:{const or '?'}"
    if name == "self._database.converge_async":
        return "converge:data"
    if name == "self._converge_addrs":
        return "converge:addrs"
    if name.startswith("self._database."):
        return f"db:{name.split('.')[-1]}"
    if name == "self._record_push_lag":
        return "lag:push"
    if name == "self._note_lag":
        return "lag:note"
    if name in ("self._h_rtt.record", "self._h_lag.record"):
        seam = "cluster.rtt" if "_h_rtt" in name else "cluster.converge_lag"
        return f"hist:{seam}"
    if name == "self._reg.trace_event":
        lits = [
            a.value
            for a in call.args[:2]
            if isinstance(a, ast.Constant) and isinstance(a.value, str)
        ]
        return "trace:" + (".".join(lits) if len(lits) == 2 else "?")
    if name == "self._reg.gauge_set":
        a = call.args[0] if call.args else None
        lit = a.value if isinstance(a, ast.Constant) else "?"
        return f"gauge:{lit}"
    if name in ("faults.point", "faults.async_point"):
        a = call.args[0] if call.args else None
        lit = a.value if isinstance(a, ast.Constant) else "?"
        return f"failpoint:{lit}"
    if name.endswith("create_task") and call.args:
        inner = call.args[0]
        if isinstance(inner, ast.Call):
            target = dotted_name(inner.func)
            if target.startswith("self._database."):
                return f"task:db.{target.split('.')[-1]}"
            if target.startswith("self."):
                return f"task:{target.split('.', 1)[1]}"
        return "task:?"
    if isinstance(call.func, ast.Attribute):
        meth = call.func.attr
        recv = dotted_name(call.func.value)
        if meth in _MUTATORS and _rooted(recv + "."):
            return f"mut:{recv}.{meth}"
    parts = name.split(".")
    if len(parts) == 2 and parts[0] == "self":
        meth = parts[1]
        if meth in _PURE_HELPERS or meth.startswith("_log"):
            return None
        return f"call:{meth}"
    return None


def _target_effects(
    target: ast.AST, out: set[str], aliases: dict[str, str] | None = None
) -> None:
    if isinstance(target, ast.Tuple):
        for elt in target.elts:
            _target_effects(elt, out, aliases)
        return
    if isinstance(target, ast.Attribute):
        dotted = dotted_name(target)
        if _rooted(dotted):
            out.add(f"set:{dotted}")
        elif aliases:
            root = dotted.split(".")[0]
            if root in aliases:
                # mutation through a local alias of a self-rooted
                # collection entry (a _PeerState, typically)
                out.add(f"set:{aliases[root]}[]")
        return
    if isinstance(target, ast.Subscript):
        dotted = dotted_name(target.value)
        if dotted == "self._stats":
            key = target.slice
            lit = key.value if isinstance(key, ast.Constant) else "?"
            out.add(f"stat:{lit}")
        elif _rooted(dotted):
            out.add(f"set:{dotted}[]")


def _rooted_source(value: ast.AST) -> str | None:
    """The self-rooted collection a local alias points into:
    `st = self._peers.get(addr)` -> 'self._peers'. Conservative: the
    FIRST self-rooted attribute anywhere in the value expression."""
    for node in ast.walk(value):
        if isinstance(node, ast.Attribute):
            dotted = dotted_name(node)
            if dotted.startswith("self.") and "." not in dotted[5:]:
                return dotted
    return None


def _collect_aliases(stmts) -> dict[str, str]:
    """{local name: 'self.<collection>'} for locals bound from a
    self-rooted lookup (or bound alongside one in a chained assignment,
    `st = self._peers[addr] = _PeerState()`). Mutating such a local IS
    mutating protocol state; without this, `st.fails = 0` would be an
    invisible effect. `self`/`conn` stay direct-rooted, never aliased."""
    aliases: dict[str, str] = {}
    for stmt in stmts:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Assign):
                continue
            src = _rooted_source(node.value)
            if src is None:
                for t in node.targets:
                    if isinstance(t, (ast.Subscript, ast.Attribute)):
                        src = _rooted_source(t)
                        if src is not None:
                            break
            if src is None:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id not in ("self", "conn"):
                    aliases[t.id] = src
    return aliases


def collect_effects(stmts) -> set[str]:
    """The canonical effect tokens of a statement list (whole subtree)."""
    out: set[str] = set()
    aliases = _collect_aliases(stmts)
    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                eff = _classify_call(node)
                if eff is not None:
                    out.add(eff)
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    _target_effects(t, out, aliases)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    base = t.value if isinstance(t, ast.Subscript) else t
                    dotted = dotted_name(base)
                    if _rooted(dotted):
                        out.add(f"mut:{dotted}.del")
    return out


# ---- handler / section extraction -------------------------------------------


def _isinstance_msgs(test: ast.AST) -> list[str] | None:
    """`isinstance(msg, X)` / `isinstance(msg, (X, Y))` -> class names."""
    if not (
        isinstance(test, ast.Call)
        and isinstance(test.func, ast.Name)
        and test.func.id == "isinstance"
        and len(test.args) == 2
        and isinstance(test.args[0], ast.Name)
        and test.args[0].id == "msg"
    ):
        return None
    spec = test.args[1]
    elts = spec.elts if isinstance(spec, ast.Tuple) else [spec]
    names = [dotted_name(e).split(".")[-1] for e in elts]
    return [n for n in names if n] or None


def _handler_branches(fn: ast.AST) -> dict[str, dict]:
    """{msg class -> {effects, line}} + the '<fallthrough>' tail entry."""
    out: dict[str, dict] = {}
    tail: list[ast.AST] = []
    for stmt in fn.body:
        msgs = (
            _isinstance_msgs(stmt.test) if isinstance(stmt, ast.If) else None
        )
        if msgs:
            effects = sorted(collect_effects(stmt.body))
            for m in msgs:
                out[m] = {"effects": effects, "line": stmt.lineno}
            tail.extend(stmt.orelse)
        else:
            tail.append(stmt)
    out["<fallthrough>"] = {
        "effects": sorted(collect_effects(tail)),
        "line": fn.lineno,
    }
    return out


def _handshake_roles(fn: ast.AST) -> dict[str, dict]:
    """Split `_handshake` effects per role on its `if active:` branches;
    statements outside those ifs count for both roles."""
    eff = {"active": set(), "passive": set()}

    def go(stmts, roles):
        for stmt in stmts:
            if (
                isinstance(stmt, ast.If)
                and isinstance(stmt.test, ast.Name)
                and stmt.test.id == "active"
            ):
                if "active" in roles:
                    go(stmt.body, ("active",))
                if "passive" in roles:
                    go(stmt.orelse, ("passive",))
            else:
                found = collect_effects([stmt])
                for r in roles:
                    eff[r] |= found

    go(fn.body, ("active", "passive"))
    return {
        role: {"effects": sorted(effs), "line": fn.lineno}
        for role, effs in eff.items()
    }


def _cluster_methods(tree: ast.AST) -> dict[str, ast.AST]:
    """Every method of the (first) class defining `_active_msg` — the
    Cluster class in the product, whatever the fixture calls it."""
    classes = [n for n in tree.body if isinstance(n, ast.ClassDef)]
    for cls in classes:
        methods = {
            m.name: m
            for m in cls.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        if "_active_msg" in methods:
            return methods
    return {}


def message_classes(root: str = ROOT, msg_rel: str = MSG_REL) -> list[str]:
    path = os.path.join(root, msg_rel)
    if not os.path.exists(path):
        return []
    src = load_source(path, root)
    return sorted(
        n.name
        for n in src.tree.body
        if isinstance(n, ast.ClassDef) and n.name.startswith("Msg")
    )


def extract(
    root: str = ROOT,
    cluster_rel: str = CLUSTER_REL,
    msg_rel: str = MSG_REL,
) -> dict:
    """The atlas as extracted from the source right now:
    {"messages": [...], "sections": {section: {key: {effects, line}}}}.
    Sections whose function is absent (partial fixtures) are skipped."""
    src = load_source(os.path.join(root, cluster_rel), root)
    methods = _cluster_methods(src.tree)
    sections: dict[str, dict[str, dict]] = {}
    for section, fname in HANDLERS.items():
        fn = methods.get(fname)
        if fn is not None:
            sections[section] = _handler_branches(fn)
    if "_handshake" in methods:
        sections["handshake"] = _handshake_roles(methods["_handshake"])
    for section, names in (("sync", SYNC_FUNCS), ("dial", DIAL_FUNCS),
                           ("send", SEND_FUNCS), ("recv", RECV_FUNCS)):
        entries = {}
        for fname in names:
            fn = methods.get(fname)
            if fn is not None:
                entries[fname] = {
                    "effects": sorted(collect_effects(fn.body)),
                    "line": fn.lineno,
                }
        if entries:
            sections[section] = entries
    return {
        "messages": message_classes(root, msg_rel),
        "sections": sections,
        "rel": src.rel,
    }


# ---- manifest ---------------------------------------------------------------


def load_manifest(path: str = PROTOCOL_MANIFEST_PATH) -> dict | None:
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def write_manifest(
    path: str = PROTOCOL_MANIFEST_PATH,
    root: str = ROOT,
    cluster_rel: str = CLUSTER_REL,
    msg_rel: str = MSG_REL,
) -> dict:
    """Regenerate the effect sets from the source, preserving the
    human-written notes; new entries get a placeholder JL1003 rejects
    until a human describes the transition."""
    atlas = extract(root, cluster_rel, msg_rel)
    existing = load_manifest(path) or {"sections": {}}
    sections = {}
    for section, entries in sorted(atlas["sections"].items()):
        old = existing.get("sections", {}).get(section, {})
        sections[section] = {
            key: {
                "effects": entry["effects"],
                "note": old.get(key, {}).get("note", PLACEHOLDER),
            }
            for key, entry in sorted(entries.items())
        }
    manifest = {
        "_comment": (
            "Generated by `python -m scripts.jlint --write-manifest` "
            "from jylis_tpu/cluster/cluster.py's handler dispatch, "
            "handshake, sync machinery, dial state machine and send "
            "path. Effects are mechanical; notes are human-written and "
            "preserved across regeneration. `make lint` fails on "
            "handler effects outside this manifest (JL1001), on silent "
            "(role, msg) fall-throughs (JL1002), and on drift/"
            "placeholder notes (JL1003). jmodel (scripts/jmodel) "
            "explores the same protocol dynamically."
        ),
        "schema": 1,
        "messages": atlas["messages"],
        "sections": sections,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
        f.write("\n")
    return manifest


# effect families that count as "observable" for the silent-ignore rule:
# a branch producing none of these does nothing a peer, operator, or
# metric can see — the exact fall-through class JL1002 forbids
_OBSERVABLE = (
    "send:", "broadcast:", "drop:", "msg_drop:", "converge:", "stat:",
    "trace:", "hist:", "gauge:", "task:", "call:", "set:", "mut:",
    "lag:", "db:", "failpoint:",
)


def _is_silent(effects: list[str]) -> bool:
    return not any(e.startswith(_OBSERVABLE) for e in effects)


def check(
    manifest_path: str = PROTOCOL_MANIFEST_PATH,
    atlas: dict | None = None,
    root: str = ROOT,
) -> list[Finding]:
    if atlas is None:
        atlas = extract(root)
    out: list[Finding] = []
    rel = os.path.relpath(manifest_path, ROOT)
    src_rel = atlas.get("rel", CLUSTER_REL)
    manifest = load_manifest(manifest_path)
    if manifest is None:
        out.append(
            Finding(
                "JL1003", rel, 1,
                "protocol manifest missing — run `python -m scripts.jlint "
                "--write-manifest`, describe each transition, commit",
                "",
            )
        )
        return out
    if manifest.get("messages") != atlas["messages"]:
        out.append(
            Finding(
                "JL1003", rel, 1,
                "message inventory drift: msg.py defines "
                f"{atlas['messages']} but the manifest declares "
                f"{manifest.get('messages')} — --write-manifest "
                "regenerates",
                "",
            )
        )
    man_sections = manifest.get("sections", {})
    for section, entries in sorted(atlas["sections"].items()):
        man_entries = man_sections.get(section, {})
        for key, entry in sorted(entries.items()):
            committed = man_entries.get(key)
            if committed is None:
                out.append(
                    Finding(
                        "JL1001", src_rel, entry["line"],
                        f"protocol atlas: `{section}` / `{key}` is not "
                        f"declared in {rel} — run --write-manifest and "
                        "describe the transition",
                        key,
                    )
                )
                continue
            extra = sorted(set(entry["effects"]) - set(committed["effects"]))
            if extra:
                out.append(
                    Finding(
                        "JL1001", src_rel, entry["line"],
                        f"`{section}` / `{key}` produces effects outside "
                        f"the manifest: {extra} — new protocol behaviour "
                        "must be declared (--write-manifest) and reviewed",
                        key,
                    )
                )
            stale = sorted(set(committed["effects"]) - set(entry["effects"]))
            if stale:
                out.append(
                    Finding(
                        "JL1003", rel, 1,
                        f"`{section}` / `{key}` declares effects no "
                        f"handler produces: {stale} — drift; "
                        "--write-manifest regenerates",
                        key,
                    )
                )
            note = committed.get("note", "")
            if not note.strip() or note.strip() == PLACEHOLDER:
                out.append(
                    Finding(
                        "JL1003", rel, 1,
                        f"`{section}` / `{key}` has no note — one line "
                        "saying what this transition means to the "
                        "protocol",
                        key,
                    )
                )
        for key in sorted(set(man_entries) - set(entries)):
            out.append(
                Finding(
                    "JL1003", rel, 1,
                    f"stale manifest entry `{section}` / `{key}`: no "
                    "such branch/function any more — --write-manifest "
                    "regenerates",
                    key,
                )
            )
    for section in sorted(set(man_sections) - set(atlas["sections"])):
        # a WHOLE section whose machinery left the source (extract()
        # skips absent functions) — entry-level drift can't see it
        out.append(
            Finding(
                "JL1003", rel, 1,
                f"stale manifest section `{section}`: none of its "
                "functions exist in the source any more — "
                "--write-manifest regenerates",
                section,
            )
        )
    # coverage + silent-ignore (JL1002): every message class must hit an
    # isinstance branch or an effectful fall-through in BOTH roles, and
    # no branch may be a silent ignore
    for section in ("role:active", "role:passive"):
        entries = atlas["sections"].get(section)
        if entries is None:
            continue
        fallthrough = entries.get("<fallthrough>", {"effects": []})
        for key, entry in sorted(entries.items()):
            if _is_silent(entry["effects"]) and key != "<fallthrough>":
                out.append(
                    Finding(
                        "JL1002", src_rel, entry["line"],
                        f"`{section}` / `{key}` ignores the message with "
                        "NO observable effect — make it a declared drop "
                        "(Cluster._drop_msg: counted + traced) or handle "
                        "it",
                        key,
                    )
                )
        for msg in atlas["messages"]:
            if msg in entries:
                continue
            if _is_silent(fallthrough["effects"]):
                out.append(
                    Finding(
                        "JL1002", src_rel, fallthrough.get("line", 1),
                        f"`{section}` has no branch for `{msg}` and its "
                        "fall-through is silent — an undeclared "
                        "(role, state, msg) hole in the protocol",
                        msg,
                    )
                )
    return out
