"""The jlint semantic core: one Project, shared by every pass.

Passes 1-6 each re-read and re-parsed the tree independently; the three
semantic passes (7-9) need whole-program facts no single parse can give.
This module is the shared substrate:

* **content-hash AST cache** — each file parses once per content hash;
  parsed trees are memoised in-process for the run AND pickled under
  ``scripts/jlint/.cache/`` keyed by sha256(text), so an unchanged file
  never re-parses across runs (the `make lint` time-budget rides this).
* **function summaries** (:class:`FuncInfo`) — per function: every call
  site with the set of locks held at it, every known-blocking primitive
  with the locks held at it, every lock acquisition (with what was
  already held — the lock-order edges), every ``await`` with the
  thread-locks held across it, and whether the function is handed to a
  thread (``threading.Thread(target=...)`` / ``asyncio.to_thread`` /
  ``run_in_executor``).
* **interprocedural queries** — the transitive blocking closure over
  resolved sync call edges (pass 1's JL101 upgrade and pass 9's JL903
  both consume it) and the global lock-acquisition graph (pass 9's
  JL902 cycle check).

Resolution discipline is graph.py's: an edge exists only when the
receiver is certain, so interprocedural findings never rest on a
guessed callee.
"""

from __future__ import annotations

import ast
import hashlib
import os
import pickle
from dataclasses import dataclass, field

from . import ROOT, Source, dotted_name, iter_py_files
from .graph import ClassInfo, ModuleInfo, ProjectGraph

CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".cache")

# mirror of pass_async's blocking model (kept in one place here so the
# intra- and inter-procedural checks can never disagree about what
# "blocking" means)
BLOCKING_CALLS = {
    "time.sleep",
    "os.fsync",
    "os.fdatasync",
    "os.replace",
    "os.rename",
    "os.remove",
    "os.truncate",
    "os.makedirs",
    "os.listdir",
    "socket.create_connection",
    "subprocess.run",
    "subprocess.check_output",
    "subprocess.check_call",
}
BLOCKING_METHOD_NAMES = {"fsync", "fdatasync", "scan_apply"}
JOURNAL_METHODS = {"open", "close", "flush", "rotate_begin", "rotate_commit"}
BLOCKING_BUILTINS = {"open"}

LOCKISH = ("lock", "_cv", "cond", "mutex")


def is_lockish(expr_src: str) -> bool:
    low = expr_src.lower()
    return any(tok in low for tok in LOCKISH)


def blocking_call_name(call: ast.Call) -> str | None:
    """The pass-1 blocking model, shared verbatim."""
    name = dotted_name(call.func)
    if name in BLOCKING_CALLS or name in BLOCKING_BUILTINS:
        return name
    if isinstance(call.func, ast.Attribute):
        meth = call.func.attr
        if meth in BLOCKING_METHOD_NAMES:
            return name or meth
        recv = dotted_name(call.func.value).lower()
        if meth in JOURNAL_METHODS and "journal" in recv:
            return name or meth
    return None


@dataclass
class CallSite:
    raw: str  # dotted source form, for messages
    targets: tuple[str, ...]  # resolved qualnames ((): opaque)
    lineno: int
    locks: tuple[str, ...]  # thread locks held (sync `with`)
    alocks: tuple[str, ...]  # asyncio locks held (`async with`)


@dataclass
class FuncInfo:
    qual: str  # "rel::Class.method" or "rel::func"
    rel: str
    cls: str | None
    name: str
    node: ast.AST
    is_async: bool
    lineno: int
    calls: list[CallSite] = field(default_factory=list)
    # (blocking-name, lineno, thread locks held at the call)
    blocking: list[tuple[str, int, tuple[str, ...]]] = field(default_factory=list)
    # (lock, lineno, locks already held, acquired-via-async-with)
    acquires: list[tuple[str, int, tuple[str, ...], bool]] = field(
        default_factory=list
    )
    # (lineno, thread locks held across the await)
    awaits: list[tuple[int, tuple[str, ...]]] = field(default_factory=list)
    # names this function dispatches to threads (resolved qualnames)
    thread_dispatch: list[str] = field(default_factory=list)


def _sha(text: str) -> str:
    # the interpreter version rides the key: pickled ast nodes from one
    # Python unpickle under another as subtly-wrong objects (missing
    # fields like FunctionDef.type_params) that crash far from here
    import sys

    tag = f"{sys.version_info.major}.{sys.version_info.minor}:"
    return hashlib.sha256((tag + text).encode()).hexdigest()


_MEM_CACHE: dict[str, ast.AST] = {}


def parse_cached(text: str, path: str) -> ast.AST:
    """Parse with the two-level content-hash cache (memory, then disk)."""
    key = _sha(text)
    tree = _MEM_CACHE.get(key)
    if tree is not None:
        return tree
    cache_path = os.path.join(CACHE_DIR, key[:2], key + ".ast")
    try:
        with open(cache_path, "rb") as f:
            tree = pickle.load(f)
    except (OSError, pickle.PickleError, EOFError, AttributeError):
        tree = None
    if tree is None:
        tree = ast.parse(text, filename=path)
        try:
            os.makedirs(os.path.dirname(cache_path), exist_ok=True)
            tmp = cache_path + f".tmp{os.getpid()}"
            with open(tmp, "wb") as f:
                pickle.dump(tree, f)
            os.replace(tmp, cache_path)
            _prune_cache()
        except OSError:
            pass  # cache is best-effort; lint correctness never depends on it
    _MEM_CACHE[key] = tree
    return tree


_CACHE_MAX_ENTRIES = 1024


def _prune_cache(max_entries: int = _CACHE_MAX_ENTRIES) -> None:
    """Bound the on-disk cache: it is keyed by content hash, so every
    file version ever linted would otherwise accumulate forever on a
    long-lived checkout. Oldest-by-mtime entries go first; runs only on
    a cache write (rare once warm)."""
    entries = []
    for dirpath, _dirs, files in os.walk(CACHE_DIR):
        for f in files:
            if f.endswith(".ast"):
                p = os.path.join(dirpath, f)
                try:
                    entries.append((os.path.getmtime(p), p))
                except OSError:
                    pass
    if len(entries) <= max_entries:
        return
    entries.sort()
    for _mtime, p in entries[: len(entries) - max_entries]:
        try:
            os.remove(p)
        except OSError:
            pass


def load_source(path: str, root: str = ROOT) -> Source:
    """Source.load through the content-hash AST cache. A file that no
    longer parses is a clean one-line diagnostic + exit 2, never a
    traceback (the pre-core CLI promised the same)."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    try:
        tree = parse_cached(text, path)
    except SyntaxError as e:
        import sys

        print(f"jlint: cannot parse {path}: {e}", file=sys.stderr)
        raise SystemExit(2) from None
    return Source.load(path, root, tree=tree)


class Project:
    """All sources in scope + the call graph + per-function summaries."""

    def __init__(self, sources: list[Source]):
        self.sources = sources
        self.by_rel: dict[str, Source] = {s.rel: s for s in sources}
        self.graph = ProjectGraph(sources)
        self.functions: dict[str, FuncInfo] = {}
        self._thread_roots: set[str] | None = None
        self._blocking_closure: dict[str, tuple[str, ...]] | None = None
        for src in sources:
            self._summarise(src)

    @classmethod
    def load(cls, root: str = ROOT, subdirs: tuple[str, ...] = ("jylis_tpu", "scripts")) -> "Project":
        out = []
        for path in iter_py_files(root, subdirs):
            out.append(load_source(path, root))
        return cls(out)

    # ---- summaries ---------------------------------------------------------

    def _summarise(self, src: Source) -> None:
        mi = self.graph.by_rel[src.rel]
        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._summarise_func(src, mi, None, node)
            elif isinstance(node, ast.ClassDef):
                ci = mi.classes.get(node.name)
                for m in node.body:
                    if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self._summarise_func(src, mi, ci, m)

    @staticmethod
    def _nested_defs(fn: ast.AST) -> dict[str, ast.AST]:
        """Function defs nested directly inside `fn`'s body (one level —
        deeper nesting summarises recursively from there)."""
        out: dict[str, ast.AST] = {}
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out[node.name] = node
                continue  # its own nested defs belong to IT
            if isinstance(node, ast.Lambda):
                continue
            stack.extend(ast.iter_child_nodes(node))
        return out

    def _lock_name(
        self, item: ast.withitem, mi: ModuleInfo, ci: ClassInfo | None,
        local_types: dict[str, str],
    ) -> str | None:
        expr = item.context_expr
        # unwrap lock.acquire-style helpers: `with self._cv:` is the idiom
        src_txt = ast.unparse(expr)
        if not is_lockish(src_txt):
            return None
        parts = dotted_name(expr).split(".")
        if parts and parts[0] in ("self", "cls") and ci is not None and len(parts) >= 2:
            # normalise per-class: Journal._cv — instance identity is not
            # statically visible; same-class self-edges are ignored by the
            # cycle check for exactly that reason
            return f"{ci.name}.{parts[-1]}"
        if parts and parts[0] in local_types and len(parts) == 2:
            return f"{local_types[parts[0]]}.{parts[1]}"
        if len(parts) == 1 and parts[0]:
            return f"{mi.rel}::{parts[0]}"
        # untyped receiver (mgr._lock where mgr's class is unknown): keep
        # the attribute tail so held-ness still registers
        return f"?.{parts[-1]}" if parts else f"?.{src_txt}"

    def _summarise_func(
        self,
        src: Source,
        mi: ModuleInfo,
        ci: ClassInfo | None,
        fn: ast.AST,
        parent_qual: str | None = None,
    ) -> None:
        if parent_qual is not None:
            qual = f"{parent_qual}.<locals>.{fn.name}"
        else:
            qual = (
                f"{src.rel}::{ci.name}.{fn.name}" if ci is not None
                else f"{src.rel}::{fn.name}"
            )
        # nested defs summarise on their own quals, and bare-name calls
        # to them from THIS body resolve locally — blocking I/O hidden
        # in a local helper must not escape the interprocedural checks
        nested = self._nested_defs(fn)
        local_funcs = {
            name: f"{qual}.<locals>.{name}" for name in nested
        }
        info = FuncInfo(
            qual=qual,
            rel=src.rel,
            cls=ci.name if ci is not None else None,
            name=fn.name,
            node=fn,
            is_async=isinstance(fn, ast.AsyncFunctionDef),
            lineno=fn.lineno,
        )
        # local constructor types: x = ClassName(...)
        local_types: dict[str, str] = {}
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                cname = dotted_name(node.value.func).split(".")[-1]
                if cname[:1].isupper():
                    local_types[node.targets[0].id] = cname

        def visit(node: ast.AST, locks: tuple[str, ...], alocks: tuple[str, ...]):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return  # nested defs are summarised on their own
            if isinstance(node, (ast.With, ast.AsyncWith)):
                is_async_with = isinstance(node, ast.AsyncWith)
                new_locks, new_alocks = locks, alocks
                for item in node.items:
                    lname = self._lock_name(item, mi, ci, local_types)
                    if lname is not None:
                        info.acquires.append(
                            (lname, node.lineno, locks + alocks, is_async_with)
                        )
                        if is_async_with:
                            new_alocks = new_alocks + (lname,)
                        else:
                            new_locks = new_locks + (lname,)
                    # the with-item expression itself may contain calls
                    visit(item.context_expr, locks, alocks)
                for stmt in node.body:
                    visit(stmt, new_locks, new_alocks)
                return
            if isinstance(node, ast.Await):
                info.awaits.append((node.lineno, locks))
            if isinstance(node, ast.Call):
                self._record_call(
                    info, node, mi, ci, local_types, locks, alocks, local_funcs
                )
            for child in ast.iter_child_nodes(node):
                visit(child, locks, alocks)

        for stmt in fn.body:
            visit(stmt, (), ())
        self.functions[qual] = info
        for sub in nested.values():
            self._summarise_func(src, mi, ci, sub, parent_qual=qual)

    def _record_call(
        self,
        info: FuncInfo,
        call: ast.Call,
        mi: ModuleInfo,
        ci: ClassInfo | None,
        local_types: dict[str, str],
        locks: tuple[str, ...],
        alocks: tuple[str, ...],
        local_funcs: dict[str, str] | None = None,
    ) -> None:
        raw = dotted_name(call.func)
        bname = blocking_call_name(call)
        if bname:
            info.blocking.append((bname, call.lineno, locks))
        # a bare name naming a NESTED def wins over any module symbol:
        # that is what the call binds to at runtime
        if (
            local_funcs
            and isinstance(call.func, ast.Name)
            and call.func.id in local_funcs
        ):
            targets: tuple[str, ...] = (local_funcs[call.func.id],)
        else:
            targets = self.graph.resolve_call(call.func, mi, ci, local_types)
        info.calls.append(
            CallSite(
                raw=raw, targets=targets, lineno=call.lineno,
                locks=locks, alocks=alocks,
            )
        )
        # thread dispatch: the CALLABLE argument runs on a thread
        cands: list[ast.AST] = []
        if raw.endswith("Thread"):
            cands += [kw.value for kw in call.keywords if kw.arg == "target"]
        elif raw.endswith("to_thread"):
            cands += call.args[:1]
        elif raw.endswith("run_in_executor"):
            cands += call.args[1:2]
        for c in cands:
            for t in self.graph.resolve_call(c, mi, ci, local_types):
                info.thread_dispatch.append(t)

    # ---- interprocedural queries ------------------------------------------

    def thread_roots(self) -> set[str]:
        """Every function dispatched to a thread anywhere in the project."""
        if self._thread_roots is None:
            roots: set[str] = set()
            for fi in self.functions.values():
                roots.update(fi.thread_dispatch)
            self._thread_roots = roots
        return self._thread_roots

    def blocking_closure(self) -> dict[str, tuple[str, ...]]:
        """qual -> a witness call chain to a blocking primitive, for every
        SYNC function whose transitive sync callees block. The chain is
        ('callee-qual', ..., 'blocking-name'), shortest-first discovery
        order; async callees are excluded (they are analysed on their
        own and awaiting them does not block the loop)."""
        if self._blocking_closure is not None:
            return self._blocking_closure
        closure: dict[str, tuple[str, ...]] = {}
        # seed: direct blockers
        for q, fi in self.functions.items():
            if fi.is_async:
                continue
            if fi.blocking:
                closure[q] = (fi.blocking[0][0],)
        changed = True
        while changed:
            changed = False
            for q, fi in self.functions.items():
                if fi.is_async or q in closure:
                    continue
                for site in fi.calls:
                    for t in site.targets:
                        callee = self.functions.get(t)
                        if callee is None or callee.is_async:
                            continue
                        if t in closure:
                            closure[q] = (t,) + closure[t]
                            changed = True
                            break
                    if q in closure:
                        break
        self._blocking_closure = closure
        return closure

    def lock_edges(self) -> dict[tuple[str, str], tuple[str, int, str]]:
        """(held, acquired) -> one witness (rel, line, via-description)
        over the whole project, including acquisitions that happen inside
        callees entered with a lock held (one call level deep per
        iteration, to a fixpoint over call-chain summaries)."""
        # per-function: locks it may acquire (directly or transitively),
        # as {lock: witness}
        acq: dict[str, dict[str, tuple[str, int, str]]] = {}
        for q, fi in self.functions.items():
            own = {}
            for lname, line, _held, _is_async in fi.acquires:
                own.setdefault(lname, (fi.rel, line, f"in {q}"))
            acq[q] = own
        changed = True
        while changed:
            changed = False
            for q, fi in self.functions.items():
                mine = acq[q]
                for site in fi.calls:
                    for t in site.targets:
                        for lname, wit in acq.get(t, {}).items():
                            if lname not in mine:
                                mine[lname] = (
                                    fi.rel, site.lineno,
                                    f"via {site.raw} -> {wit[2]}",
                                )
                                changed = True
        # '?.<attr>' identities (untyped receivers) stay OUT of the
        # edge set: the wildcard merges every same-named attribute lock
        # across unrelated classes into one node, which would fabricate
        # cycle edges between locks that can never be the same object —
        # the exact false-edge class this module's resolution discipline
        # forbids. (They still count as HELD for the JL903 blocking
        # analysis, where over-approximation is conservative.)
        def concrete(name: str) -> bool:
            return not name.startswith("?.")

        edges: dict[tuple[str, str], tuple[str, int, str]] = {}
        for q, fi in self.functions.items():
            # direct: acquire B while holding A in one function
            for lname, line, held, _is_async in fi.acquires:
                for h in held:
                    if h != lname and concrete(h) and concrete(lname):
                        edges.setdefault((h, lname), (fi.rel, line, f"in {q}"))
            # interprocedural: call with A held, callee acquires B
            for site in fi.calls:
                held = site.locks + site.alocks
                if not held:
                    continue
                for t in site.targets:
                    for lname, wit in acq.get(t, {}).items():
                        for h in held:
                            if h != lname and concrete(h) and concrete(lname):
                                edges.setdefault(
                                    (h, lname),
                                    (fi.rel, site.lineno,
                                     f"{site.raw} acquires {lname} ({wit[2]})"),
                                )
        return edges
