"""A lightweight C++ front-end for the disciplined subset native/ uses.

jlint pass 11 (``pass_semantics``) needs to reason about the native
serving dispatch SYMBOLICALLY — per-command argument grammar,
validation predicates, reply shapes — which the regex extraction of
pass 3 cannot see.  Rather than grow a libclang dependency (not in the
image, and overkill for five small translation units), this module
implements a tokenizer + recursive-descent parser over the subset the
native tree actually exercises.  The contract (enforced by
tests/test_jlint.py parse-fidelity tests and documented in
docs/development.md) is:

* preprocessor: ``#include`` / ``#pragma once`` lines only — skipped
  wholesale; no conditional compilation, no macro definitions;
* declarations: free functions (incl. ``inline`` / ``static``),
  ``extern "C" { ... }`` blocks, (anonymous) ``namespace { ... }``,
  ``struct``/``class`` definitions with fields, methods, ``operator``
  overloads and default member initializers; ``using`` aliases;
* NO template *declarations* (template-id *uses* like
  ``std::vector<TlogEnt>`` tokenize fine), no raw strings, no
  ``switch``/``goto``, no multiple inheritance, no exceptions;
* statements: ``if``/``else``, ``while``, ``for`` (incl. range-for),
  ``do``/``while``, ``return``, ``break``/``continue``, blocks, and
  generic expression/declaration statements (lambdas and initializer
  braces parse as opaque, brace-matched token groups).

The result is a ``Unit``: every function (struct methods qualified as
``Struct::name``) with its body parsed to a statement tree whose
conditions and expressions stay token lists — exactly the level the
semantic extractor needs, with no pretence of full C++ fidelity.
"""

from __future__ import annotations

from dataclasses import dataclass, field

# ---- tokens ----------------------------------------------------------------

# longest-match punctuator table (subset-relevant operators only)
_PUNCTS = [
    "<<=", ">>=", "...", "->*",
    "::", "->", "<<", ">>", "<=", ">=", "==", "!=", "&&", "||",
    "+=", "-=", "*=", "/=", "%=", "&=", "|=", "^=", "++", "--",
    "{", "}", "(", ")", "[", "]", ";", ",", ".", "?", ":",
    "+", "-", "*", "/", "%", "&", "|", "^", "~", "!", "<", ">", "=",
]


@dataclass(frozen=True)
class Token:
    kind: str  # 'ident' | 'num' | 'str' | 'char' | 'punct'
    text: str
    line: int


class CppParseError(Exception):
    """Raised when the source leaves the disciplined subset."""

    def __init__(self, msg: str, line: int):
        super().__init__(f"line {line}: {msg}")
        self.line = line


def tokenize(text: str) -> list[Token]:
    toks: list[Token] = []
    i, n, line = 0, len(text), 1
    while i < n:
        c = text[i]
        if c == "\n":
            line += 1
            i += 1
            continue
        if c in " \t\r\f\v":
            i += 1
            continue
        if c == "/" and i + 1 < n and text[i + 1] == "/":
            while i < n and text[i] != "\n":
                i += 1
            continue
        if c == "/" and i + 1 < n and text[i + 1] == "*":
            j = text.find("*/", i + 2)
            if j < 0:
                raise CppParseError("unterminated block comment", line)
            line += text.count("\n", i, j)
            i = j + 2
            continue
        if c == "#" and (not toks or toks[-1].line != line):
            # preprocessor directive: skip the whole line (the subset
            # has no continuations and no conditional compilation)
            while i < n and text[i] != "\n":
                i += 1
            continue
        if c in "\"'":
            quote, j = c, i + 1
            while j < n:
                if text[j] == "\\":
                    j += 2
                    continue
                if text[j] == quote:
                    break
                if text[j] == "\n":
                    raise CppParseError("newline in literal", line)
                j += 1
            if j >= n:
                raise CppParseError("unterminated literal", line)
            toks.append(
                Token("str" if quote == '"' else "char", text[i : j + 1], line)
            )
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and text[i + 1].isdigit()):
            j = i + 1
            while j < n and (text[j].isalnum() or text[j] in "._'"):
                # 1e-5 / 0x1p-3 exponent signs ride the number token
                if text[j] in "eEpP" and j + 1 < n and text[j + 1] in "+-":
                    j += 1
                j += 1
            toks.append(Token("num", text[i:j], line))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i + 1
            while j < n and (text[j].isalnum() or text[j] == "_"):
                j += 1
            toks.append(Token("ident", text[i:j], line))
            i = j
            continue
        for p in _PUNCTS:
            if text.startswith(p, i):
                toks.append(Token("punct", p, line))
                i += len(p)
                break
        else:
            raise CppParseError(f"unexpected character {c!r}", line)
    return toks


# ---- group tree (brace/paren/bracket matching) -----------------------------

_OPEN = {"{": "}", "(": ")", "[": "]"}


@dataclass
class Group:
    open: str  # '{' | '(' | '['
    items: list  # Token | Group
    line: int

    def tokens(self) -> list[Token]:
        """Flattened token stream including the delimiters."""
        out = [Token("punct", self.open, self.line)]
        for it in self.items:
            out.extend(it.tokens() if isinstance(it, Group) else [it])
        out.append(Token("punct", _OPEN[self.open], self.line))
        return out


def _group(toks: list[Token], i: int, closer: str | None) -> tuple[list, int]:
    items: list = []
    while i < len(toks):
        t = toks[i]
        if t.kind == "punct" and t.text in _OPEN:
            inner, i = _group(toks, i + 1, _OPEN[t.text])
            items.append(Group(t.text, inner, t.line))
            continue
        if t.kind == "punct" and t.text in ")}]":
            if t.text != closer:
                raise CppParseError(f"mismatched {t.text!r}", t.line)
            return items, i + 1
        items.append(t)
        i += 1
    if closer is not None:
        raise CppParseError(f"missing closing {closer!r}", toks[-1].line)
    return items, i


def group_tree(toks: list[Token]) -> list:
    items, _ = _group(toks, 0, None)
    return items


# ---- statements ------------------------------------------------------------


@dataclass
class Block:
    stmts: list = field(default_factory=list)


@dataclass
class If:
    cond: list  # Token | Group
    then: Block
    orelse: Block | None
    line: int


@dataclass
class Loop:
    kind: str  # 'for' | 'while' | 'do'
    header: list  # Token | Group (the paren group's items)
    body: Block
    line: int


@dataclass
class Return:
    value: list  # Token | Group (may be empty)
    line: int


@dataclass
class Jump:
    kind: str  # 'break' | 'continue'
    line: int


@dataclass
class ExprStmt:
    """An expression or declaration statement, kept as matched tokens
    (covers assignments, calls, declarations, lambdas, structured
    bindings — anything the extractor reads but never executes)."""

    items: list  # Token | Group
    line: int


def _is_tok(it, text: str) -> bool:
    return isinstance(it, Token) and it.text == text


def parse_block(items: list) -> Block:
    block = Block()
    i = 0
    while i < len(items):
        stmt, i = _parse_stmt(items, i)
        if stmt is not None:
            block.stmts.append(stmt)
    return block


def _parse_stmt(items: list, i: int):
    it = items[i]
    if isinstance(it, Group) and it.open == "{":
        return parse_block(it.items), i + 1
    if _is_tok(it, ";"):
        return None, i + 1
    if _is_tok(it, "if"):
        if i + 1 >= len(items) or not (
            isinstance(items[i + 1], Group) and items[i + 1].open == "("
        ):
            raise CppParseError("if without condition", it.line)
        cond = items[i + 1].items
        then, j = _parse_stmt_as_block(items, i + 2)
        orelse = None
        if j < len(items) and _is_tok(items[j], "else"):
            orelse, j = _parse_stmt_as_block(items, j + 1)
        return If(cond, then, orelse, it.line), j
    if _is_tok(it, "while") or _is_tok(it, "for"):
        if i + 1 >= len(items) or not (
            isinstance(items[i + 1], Group) and items[i + 1].open == "("
        ):
            raise CppParseError(f"{it.text} without header", it.line)
        header = items[i + 1].items
        body, j = _parse_stmt_as_block(items, i + 2)
        return Loop(it.text, header, body, it.line), j
    if _is_tok(it, "do"):
        body, j = _parse_stmt_as_block(items, i + 1)
        if not (j + 1 < len(items) and _is_tok(items[j], "while")):
            raise CppParseError("do without while", it.line)
        header = items[j + 1].items
        j += 2
        if j < len(items) and _is_tok(items[j], ";"):
            j += 1
        return Loop("do", header, body, it.line), j
    if _is_tok(it, "return"):
        value = []
        j = i + 1
        while j < len(items) and not _is_tok(items[j], ";"):
            value.append(items[j])
            j += 1
        return Return(value, it.line), j + 1
    if _is_tok(it, "break") or _is_tok(it, "continue"):
        j = i + 1
        if j < len(items) and _is_tok(items[j], ";"):
            j += 1
        return Jump(it.text, it.line), j
    # expression / declaration statement: everything up to the next
    # top-level ';' (groups are atomic, so lambda bodies and init
    # braces never leak a spurious terminator)
    expr = []
    j = i
    while j < len(items) and not _is_tok(items[j], ";"):
        expr.append(items[j])
        j += 1
    return ExprStmt(expr, it.line if isinstance(it, Token) else it.line), j + 1


def _parse_stmt_as_block(items: list, i: int) -> tuple[Block, int]:
    stmt, j = _parse_stmt(items, i)
    if isinstance(stmt, Block):
        return stmt, j
    b = Block()
    if stmt is not None:
        b.stmts.append(stmt)
    return b, j


# ---- declarations ----------------------------------------------------------


@dataclass
class Function:
    name: str  # 'resp_scan', 'TlogTable::intern', 'TlogEnt::operator=='
    params: Group
    body: Block
    line: int
    ret: list = field(default_factory=list)  # return-type tokens


@dataclass
class Struct:
    name: str
    line: int
    methods: list = field(default_factory=list)  # Function, qualified names


@dataclass
class Unit:
    path: str
    functions: dict = field(default_factory=dict)  # name -> Function
    structs: dict = field(default_factory=dict)  # name -> Struct
    constants: dict = field(default_factory=dict)  # name -> literal text


# trailers legal between a function's parameter list and its body
_TRAILER_WORDS = {"const", "noexcept", "override", "final"}


def _function_from_pending(pending: list, body: Group, owner: str | None):
    """Recognize ``... name ( params ) trailers* { body }`` in the
    declaration tokens accumulated since the last ';'/'}' — or return
    None (an initializer like ``uint64_t served[5] = {0};``)."""
    # a top-level '=' means brace-initializer, never a function body
    if any(_is_tok(t, "=") for t in pending):
        return None
    # locate the parameter list: the last '(' group that is followed
    # only by trailers or a constructor init-list
    for k in range(len(pending) - 1, -1, -1):
        it = pending[k]
        if not (isinstance(it, Group) and it.open == "("):
            continue
        rest = pending[k + 1 :]
        ok = True
        in_ctor_init = False
        for r in rest:
            if isinstance(r, Token) and r.text in _TRAILER_WORDS:
                continue
            if _is_tok(r, ":"):
                in_ctor_init = True
                continue
            if in_ctor_init:
                continue  # member(init), commas — all legal
            if isinstance(r, Token) and r.text == "->":
                in_ctor_init = True  # trailing return type: same skip
                continue
            ok = False
            break
        if not ok:
            continue
        # the name precedes the parameter group
        name = None
        if k >= 1 and isinstance(pending[k - 1], Token):
            prev = pending[k - 1]
            if prev.kind == "ident" and prev.text != "operator":
                name = prev.text
            elif prev.kind == "punct" and k >= 2 and _is_tok(
                pending[k - 2], "operator"
            ):
                name = "operator" + prev.text
        elif (
            k >= 2
            and isinstance(pending[k - 1], Group)
            and pending[k - 1].open == "("
            and not pending[k - 1].items
            and _is_tok(pending[k - 2], "operator")
        ):
            name = "operator()"
        if name is None:
            continue
        if name in ("if", "while", "for", "switch", "return"):
            return None
        qual = f"{owner}::{name}" if owner else name
        ret = [t for t in pending[: k - 1]]
        return Function(qual, it, parse_block(body.items), body.line, ret)
    return None


def _scan_constants(pending: list, constants: dict) -> None:
    """Record ``constexpr <type> NAME = <literal...>;`` declarations —
    the dispatch thresholds pass 11 folds into the manifest."""
    if not any(_is_tok(t, "constexpr") for t in pending):
        return
    for k, it in enumerate(pending):
        if _is_tok(it, "="):
            if k >= 1 and isinstance(pending[k - 1], Token) and pending[
                k - 1
            ].kind == "ident":
                value = " ".join(
                    t.text
                    for t in pending[k + 1 :]
                    if isinstance(t, Token)
                )
                constants[pending[k - 1].text] = value
            return


def _parse_scope(items: list, unit: Unit, owner: str | None) -> None:
    pending: list = []
    i = 0
    while i < len(items):
        it = items[i]
        if _is_tok(it, "extern") and i + 2 < len(items) and isinstance(
            items[i + 1], Token
        ) and items[i + 1].kind == "str" and isinstance(
            items[i + 2], Group
        ) and items[i + 2].open == "{":
            _parse_scope(items[i + 2].items, unit, owner)
            pending = []
            i += 3
            continue
        if _is_tok(it, "namespace") and not (
            pending and _is_tok(pending[-1], "using")
        ):
            j = i + 1
            if j < len(items) and isinstance(items[j], Token) and items[
                j
            ].kind == "ident":
                j += 1
            if j < len(items) and isinstance(items[j], Group) and items[
                j
            ].open == "{":
                _parse_scope(items[j].items, unit, owner)
                pending = []
                i = j + 1
                continue
            raise CppParseError("unsupported namespace form", it.line)
        if (
            (_is_tok(it, "struct") or _is_tok(it, "class"))
            and not pending
            and i + 2 < len(items)
            and isinstance(items[i + 1], Token)
            and isinstance(items[i + 2], Group)
            and items[i + 2].open == "{"
        ):
            name = items[i + 1].text
            st = Struct(name, it.line)
            _parse_scope(items[i + 2].items, unit, name)
            st.methods = [
                f for f in unit.functions.values()
                if f.name.startswith(name + "::")
            ]
            unit.structs[name] = st
            i += 3
            if i < len(items) and _is_tok(items[i], ";"):
                i += 1
            continue
        if isinstance(it, Group) and it.open == "{":
            fn = _function_from_pending(pending, it, owner)
            if fn is not None:
                unit.functions[fn.name] = fn
                pending = []
                i += 1
                continue
            # brace initializer inside a declaration: keep accumulating
            pending.append(it)
            i += 1
            continue
        if _is_tok(it, ";"):
            _scan_constants(pending, unit.constants)
            pending = []
            i += 1
            continue
        pending.append(it)
        i += 1
    if pending and any(
        isinstance(p, Group) and p.open == "(" for p in pending
    ) and any(isinstance(p, Group) and p.open == "{" for p in pending):
        raise CppParseError(
            "trailing unparsed declaration", pending[0].line
        )


def parse(text: str, path: str = "<string>") -> Unit:
    unit = Unit(path)
    _parse_scope(group_tree(tokenize(text)), unit, None)
    return unit


def parse_file(path: str) -> Unit:
    with open(path, encoding="utf-8") as f:
        return parse(f.read(), path)


# ---- walk / render helpers -------------------------------------------------


def walk(block: Block):
    """Yield every statement in the tree, depth-first, pre-order."""
    for s in block.stmts:
        yield s
        if isinstance(s, If):
            yield from walk(s.then)
            if s.orelse is not None:
                yield from walk(s.orelse)
        elif isinstance(s, Loop):
            yield from walk(s.body)
        elif isinstance(s, Block):
            yield from walk(s)


def flat_tokens(items: list) -> list[Token]:
    out: list[Token] = []
    for it in items:
        if isinstance(it, Group):
            out.extend(it.tokens())
        else:
            out.append(it)
    return out


def render(items: list) -> str:
    """Canonical one-space-separated text of a token/group list — the
    form extraction predicates and manifest strings are written in."""
    return " ".join(t.text for t in flat_tokens(items))


def find_calls(items: list, name: str):
    """Yield the argument Group of every ``name ( ... )`` call found
    anywhere (recursively) in a token/group list."""
    for idx, it in enumerate(items):
        if isinstance(it, Group):
            if (
                it.open == "("
                and idx > 0
                and isinstance(items[idx - 1], Token)
                and items[idx - 1].text == name
            ):
                yield it
            yield from find_calls(it.items, name)


def split_args(group: Group) -> list[list]:
    """Split a paren Group's items on top-level commas."""
    args: list[list] = [[]]
    for it in group.items:
        if _is_tok(it, ","):
            args.append([])
        else:
            args[-1].append(it)
    return args if args != [[]] else []
