"""jlint CLI: `python -m scripts.jlint` (what `make lint` runs).

Exit 0 only when every pass is clean: no unsuppressed finding, no stale
baseline entry, no parity drift. `--write-manifest` regenerates the
pass-3 parity manifest in place and exits (commit the diff).
"""

from __future__ import annotations

import argparse
import os
import sys

from . import (
    ROOT,
    Source,
    apply_baseline,
    apply_suppressions,
    iter_py_files,
    load_baseline,
)
from . import (
    pass_async,
    pass_failpoints,
    pass_jax,
    pass_lanes,
    pass_metrics,
    pass_parity,
)

# pass 1 + JL001 cover the product and its scripts; tests are excluded
# (fixtures deliberately violate the rules), and jlint's own fixtures
# live inside string literals so the package itself stays in scope
ASYNC_SCOPE = ("jylis_tpu", "scripts")
JAX_SCOPE = ("jylis_tpu/ops",)


def collect_sources(subdirs) -> list[Source]:
    out = []
    for path in iter_py_files(ROOT, subdirs):
        try:
            out.append(Source.load(path))
        except SyntaxError as e:
            print(f"jlint: cannot parse {path}: {e}", file=sys.stderr)
            raise SystemExit(2)
    return out


def run_all(root: str = ROOT, verbose: bool = False) -> int:
    async_sources = collect_sources(ASYNC_SCOPE)
    jax_sources = [
        s for s in async_sources
        if s.rel.startswith(JAX_SCOPE[0].replace("/", os.sep))
    ]
    findings = pass_async.run(async_sources)
    findings += pass_jax.run(jax_sources)
    # pass 6 runs before suppression handling: its JL601 findings live
    # in product files and honor `# jlint: lane-shared-ok`
    findings += pass_lanes.check()
    by_rel = {s.rel: s for s in async_sources}
    apply_suppressions(findings, by_rel)
    problems = apply_baseline(findings, load_baseline())
    findings += pass_parity.check()
    findings += pass_failpoints.check()
    findings += pass_metrics.check()
    findings += problems

    bad = [f for f in findings if not f.suppressed]
    shown = findings if verbose else bad
    for f in sorted(shown, key=lambda f: (f.path, f.line, f.rule)):
        tag = " (suppressed)" if f.suppressed else ""
        print(f.render() + tag)
    n_sup = sum(1 for f in findings if f.suppressed)
    print(
        f"jlint: {len(bad)} finding(s), {n_sup} suppressed "
        f"({len(async_sources)} files, 6 passes)"
    )
    return 1 if bad else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="jlint")
    ap.add_argument(
        "--write-manifest", action="store_true",
        help="regenerate scripts/jlint/parity_manifest.json and "
        "failpoints_manifest.json (descriptions preserved) and exit",
    )
    ap.add_argument(
        "-v", "--verbose", action="store_true",
        help="also print suppressed findings",
    )
    args = ap.parse_args(argv)
    if args.write_manifest:
        manifest = pass_parity.write_manifest()
        n = sum(len(v) for v in manifest["native"].values())
        p = sum(len(v) for v in manifest["python"].values())
        print(f"parity manifest written: {n} native, {p} python commands")
        fps = pass_failpoints.write_manifest()
        todo = sum(1 for d in fps.values() if d == pass_failpoints.PLACEHOLDER)
        print(
            f"failpoints manifest written: {len(fps)} failpoints"
            + (f" ({todo} need descriptions)" if todo else "")
        )
        mets = pass_metrics.write_manifest()
        todo = sum(1 for d in mets.values() if d == pass_metrics.PLACEHOLDER)
        print(
            f"metrics manifest written: {len(mets)} metrics"
            + (f" ({todo} need descriptions)" if todo else "")
        )
        lns = pass_lanes.write_manifest()
        todo = sum(1 for d in lns.values() if d == pass_lanes.PLACEHOLDER)
        print(
            f"lanes manifest written: {len(lns)} module-level mutables"
            + (f" ({todo} need descriptions)" if todo else "")
        )
        return 0
    return run_all(verbose=args.verbose)


if __name__ == "__main__":
    raise SystemExit(main())
