"""jlint CLI: `python -m scripts.jlint` (what `make lint` runs).

Exit 0 only when every pass is clean: no unsuppressed finding, no stale
baseline entry or inline suppression, no manifest drift. One semantic
core (scripts/jlint/core.py) is built per run — content-hash-cached
ASTs, call graph, per-function summaries — and all eleven passes
consume it.

* ``--write-manifest`` regenerates every committed manifest (parity,
  failpoints, metrics, lanes, codec, lattice + the generated lattice
  property harness, protocol atlas, semantics + the generated
  differential fuzz harness) in place and exits: commit the diff.
* ``--write-corpus`` regenerates the golden codec corpus
  (tests/golden/codec_corpus.json) from the current codec manifest and
  the golden semantic-fuzz corpus (tests/golden/semfuzz_corpus.json)
  from the current semantics manifest (imports the product; run after
  any --write-manifest that changed either manifest).
* ``--out PATH`` writes machine-readable findings JSON (rule, path,
  line, message, suppressed) plus per-pass wall times — the CI artifact
  finding-count drift is diffed across.
* ``--budget`` enforces the recorded wall-time bound in
  scripts/jlint/budget.json: eleven passes must not erode the commit
  loop, so `make lint` fails if the run blows the budget.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from . import (
    ROOT,
    apply_baseline,
    apply_suppressions,
    check_inline_suppressions,
    load_baseline,
)
from . import (
    pass_async,
    pass_codec,
    pass_failpoints,
    pass_jax,
    pass_lanes,
    pass_lattice,
    pass_locks,
    pass_metrics,
    pass_parity,
    pass_protocol,
    pass_semantics,
)
from .core import Project

# pass 1 + JL001 cover the product and its scripts; tests are excluded
# (fixtures deliberately violate the rules), and jlint's own fixtures
# live inside string literals so the package itself stays in scope
ASYNC_SCOPE = ("jylis_tpu", "scripts")
JAX_SCOPE = ("jylis_tpu/ops",)

BUDGET_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "budget.json")

N_PASSES = 11


def run_all(
    root: str = ROOT,
    verbose: bool = False,
    out_path: str | None = None,
    budget: bool = False,
) -> int:
    times: dict[str, float] = {}
    t0 = time.perf_counter()
    try:
        project = Project.load(root, ASYNC_SCOPE)
    except SystemExit as e:
        # a file that no longer parses: the diagnostic already printed;
        # still write the artifact so the red build's upload explains
        # itself instead of silently missing
        if out_path:
            with open(out_path, "w", encoding="utf-8") as f:
                json.dump(
                    {"findings": [], "error": "unparseable source — see "
                     "stderr diagnostic", "exit": e.code or 2}, f, indent=2,
                )
                f.write("\n")
        return e.code or 2
    times["load"] = time.perf_counter() - t0

    def timed(name, fn, *args):
        t = time.perf_counter()
        result = fn(*args)
        times[name] = times.get(name, 0.0) + (time.perf_counter() - t)
        return result

    async_sources = project.sources
    jax_sources = [
        s for s in async_sources
        if s.rel.startswith(JAX_SCOPE[0].replace("/", os.sep))
    ]
    # line-anchored, slug-suppressable passes first: their pre-suppression
    # union is what the inline-staleness check (JL003) runs against
    findings = timed("1:async", pass_async.run, async_sources)
    findings += timed("1:async", pass_async.run_interprocedural, project)
    findings += timed("2:jax", pass_jax.run, jax_sources)
    findings += timed("6:lanes", pass_lanes.check)
    findings += timed("8:lattice", pass_lattice.run, project)
    findings += timed("9:locks", pass_locks.run, project)
    by_rel = project.by_rel
    hygiene = timed("0:suppressions", check_inline_suppressions, findings, by_rel)
    apply_suppressions(findings, by_rel)
    problems = apply_baseline(findings, load_baseline())
    findings += timed("3:parity", pass_parity.check)
    findings += timed("4:failpoints", pass_failpoints.check)
    findings += timed("5:metrics", pass_metrics.check)
    findings += timed("7:codec", pass_codec.check)
    findings += timed("10:protocol", pass_protocol.check)
    findings += timed("11:semantics", pass_semantics.check)
    findings += timed("8:lattice", pass_lattice.check_manifest, project)
    findings += problems
    findings += hygiene

    bad = [f for f in findings if not f.suppressed]
    shown = findings if verbose else bad
    for f in sorted(shown, key=lambda f: (f.path, f.line, f.rule)):
        tag = " (suppressed)" if f.suppressed else ""
        print(f.render() + tag)
    n_sup = sum(1 for f in findings if f.suppressed)
    total = time.perf_counter() - t0
    print(
        f"jlint: {len(bad)} finding(s), {n_sup} suppressed "
        f"({len(async_sources)} files, {N_PASSES} passes, {total:.2f}s)"
    )
    if verbose:
        for name in sorted(times):
            print(f"  {name:>16}: {times[name] * 1000:7.1f} ms")

    rc = 1 if bad else 0
    # budget BEFORE the artifact, so the recorded exit matches the
    # process's: an over-budget clean run must not upload "exit": 0
    if budget:
        try:
            with open(BUDGET_PATH, encoding="utf-8") as f:
                bound = json.load(f)["budget_seconds"]
        except (OSError, KeyError, ValueError):
            print("jlint: budget.json missing/unreadable — recording skipped",
                  file=sys.stderr)
            bound = None
        if bound is not None and total > bound:
            print(
                f"jlint: BUDGET EXCEEDED — {total:.2f}s > {bound:.1f}s "
                "(scripts/jlint/budget.json). Eleven passes must not erode "
                "the commit loop: profile with -v, fix the slow pass, or "
                "re-record the bound with a justification.",
                file=sys.stderr,
            )
            rc = rc or 3
    if out_path:
        payload = {
            "findings": [
                {
                    "rule": f.rule, "path": f.path, "line": f.line,
                    "msg": f.msg, "suppressed": f.suppressed,
                    "baseline": f.baseline,
                }
                for f in sorted(
                    findings, key=lambda f: (f.path, f.line, f.rule)
                )
            ],
            "counts": {
                "unsuppressed": len(bad),
                "suppressed": n_sup,
                "files": len(async_sources),
                "passes": N_PASSES,
                # ROADMAP item 1's native-surface gap as a tracked number:
                # commands only the Python oracle serves (MAP/BCOUNT/
                # SESSION/…) — moving it means re-recording the parity
                # manifest, and check_prose pins the documented figure
                "python_only": sum(
                    len(v)
                    for v in pass_parity.build_manifest()[
                        "python_only"
                    ].values()
                ),
            },
            "pass_seconds": {k: round(v, 4) for k, v in sorted(times.items())},
            "total_seconds": round(total, 4),
            "exit": rc,
        }
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2)
            f.write("\n")
    return rc


def write_manifests(project: Project | None = None) -> None:
    manifest = pass_parity.write_manifest()
    n = sum(len(v) for v in manifest["native"].values())
    p = sum(len(v) for v in manifest["python"].values())
    print(f"parity manifest written: {n} native, {p} python commands")
    fps = pass_failpoints.write_manifest()
    todo = sum(1 for d in fps.values() if d == pass_failpoints.PLACEHOLDER)
    print(
        f"failpoints manifest written: {len(fps)} failpoints"
        + (f" ({todo} need descriptions)" if todo else "")
    )
    mets = pass_metrics.write_manifest()
    todo = sum(1 for d in mets.values() if d == pass_metrics.PLACEHOLDER)
    print(
        f"metrics manifest written: {len(mets)} metrics"
        + (f" ({todo} need descriptions)" if todo else "")
    )
    lns = pass_lanes.write_manifest()
    todo = sum(1 for d in lns.values() if d == pass_lanes.PLACEHOLDER)
    print(
        f"lanes manifest written: {len(lns)} module-level mutables"
        + (f" ({todo} need descriptions)" if todo else "")
    )
    cdc = pass_codec.write_manifest()
    print(
        f"codec manifest written: {len(cdc['units'])} units, "
        f"schema v{cdc['schema_version']} (+legacy "
        f"{cdc['legacy_snapshot_versions']}) — if it changed, re-record "
        "the corpus with --write-corpus"
    )
    if project is None:
        project = Project.load(ROOT, ASYNC_SCOPE)
    lat = pass_lattice.write_manifest(project)
    print(
        f"lattice manifest written: {len(lat['merge_roots'])} merge roots, "
        f"{len(lat['types'])} harness types (tests/test_lattice_laws.py "
        "regenerated)"
    )
    proto = pass_protocol.write_manifest()
    n_entries = sum(len(v) for v in proto["sections"].values())
    todo = sum(
        1
        for sec in proto["sections"].values()
        for e in sec.values()
        if e["note"] == pass_protocol.PLACEHOLDER
    )
    print(
        f"protocol manifest written: {n_entries} transitions across "
        f"{len(proto['sections'])} sections"
        + (f" ({todo} need notes)" if todo else "")
    )
    sem = pass_semantics.write_manifest()
    todo = sum(
        1
        for e in sem["commands"].values()
        if e["note"] == pass_semantics.PLACEHOLDER
    )
    diverged = sum(
        1 for e in sem["commands"].values() if e["divergences"]
    )
    print(
        f"semantics manifest written: {len(sem['commands'])} commands, "
        f"{diverged} with divergences (tests/test_semantic_fuzz.py "
        "regenerated)"
        + (f" ({todo} need notes)" if todo else "")
        + " — if it changed, re-record the corpus with --write-corpus"
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="jlint")
    ap.add_argument(
        "--write-manifest", action="store_true",
        help="regenerate every committed manifest (parity, failpoints, "
        "metrics, lanes, codec, lattice + property harness, protocol, "
        "semantics + fuzz harness; descriptions preserved) and exit",
    )
    ap.add_argument(
        "--write-corpus", action="store_true",
        help="regenerate tests/golden/codec_corpus.json from the current "
        "codec manifest (imports the product) and exit",
    )
    ap.add_argument(
        "--out", metavar="PATH",
        help="write machine-readable findings JSON (the CI artifact)",
    )
    ap.add_argument(
        "--budget", action="store_true",
        help="fail (exit 3) when the run exceeds the recorded wall-time "
        "bound in scripts/jlint/budget.json",
    )
    ap.add_argument(
        "-v", "--verbose", action="store_true",
        help="also print suppressed findings and per-pass times",
    )
    args = ap.parse_args(argv)
    if args.write_manifest:
        write_manifests()
        return 0
    if args.write_corpus:
        corpus = pass_codec.write_corpus()
        print(
            f"codec corpus written: {len(corpus['entries'])} entries "
            f"pinned to manifest {corpus['manifest_sha256'][:12]}"
        )
        from .. import gen_semfuzz

        sem = pass_semantics._load_committed()
        fuzz = gen_semfuzz.write_corpus(sem, pass_semantics.manifest_sha())
        print(
            f"semfuzz corpus written: {len(fuzz['streams'])} streams "
            f"pinned to manifest {fuzz['manifest_sha256'][:12]}"
        )
        return 0
    return run_all(verbose=args.verbose, out_path=args.out, budget=args.budget)


if __name__ == "__main__":
    raise SystemExit(main())
