"""Pass 1 — async/thread safety (rules JL001, JL101–JL104).

The serving stack is one asyncio event loop sharing state with the
journal writer thread (`jylis_tpu/journal/journal.py`) and with
`asyncio.to_thread` drain workers (`jylis_tpu/models/manager.py`). The
failure modes this pass mechanises were all found (or nearly shipped)
by hand:

* JL101 — a known-blocking call (`os.fsync`, `time.sleep`, socket
  connect, journal lifecycle methods, engine FFI entry points) executed
  directly inside an ``async def``: every client on the loop stalls for
  its duration. Dispatch through ``asyncio.to_thread`` /
  ``run_in_executor`` instead (passing the function, not calling it,
  which is why wrapped call sites don't trigger).
* JL102 — an attribute mutated both from a thread-entry method
  (a ``threading.Thread`` target or an ``asyncio.to_thread`` callee,
  transitively) and from loop-side methods, where some mutation site is
  not under a ``with <lock/cv>`` block. Declare the guard or annotate
  the ownership protocol with ``# jlint: shared-ok``.
* JL103 — read-modify-write of a ``self.`` attribute spanning an
  ``await``: the loop can interleave another coroutine between the read
  and the write, losing one side's update.
* JL104 — blocking disk I/O (fsync/rename/open/…) performed while
  holding a thread lock or condition variable: any other thread —
  including the event loop calling a brief enqueue — blocks behind the
  disk for the duration.
* JL001 — ``except Exception`` / bare ``except`` without an explicit
  justification (``# jlint: broad-ok``): swallowing everything hides
  hot-path bugs until they cost a re-record.
"""

from __future__ import annotations

import ast

from . import Finding, Source, dotted_name, parent_map

# ONE blocking model, owned by the semantic core so the syntactic and
# interprocedural JL101 can never disagree about what "blocking" means
# (they HAD diverged when this was a local copy: os.listdir was known
# only to the core, so inlining a flagged helper hid the finding)
from .core import (  # noqa: F401  (re-exported for fixtures/tests)
    BLOCKING_BUILTINS,
    BLOCKING_CALLS,
    BLOCKING_METHOD_NAMES,
    JOURNAL_METHODS,
    LOCKISH,
    blocking_call_name as _blocking_call_name,
    is_lockish as _is_lockish,
)

# disk-touching calls that must not run under a held thread lock
LOCK_IO_CALLS = {
    "os.fsync",
    "os.fdatasync",
    "os.replace",
    "os.rename",
    "os.remove",
    "os.truncate",
}
LOCK_IO_METHOD_NAMES = {"fsync", "fdatasync"}


def _self_attr(node: ast.AST) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _enclosing_function(node: ast.AST, parents) -> ast.AST | None:
    while node in parents:
        node = parents[node]
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return node
    return None


def _under_lock_with(node: ast.AST, parents) -> bool:
    """True when an ancestor sync `with` statement's context expression
    names a lock/condition (the asyncio `async with` case is the loop's
    own serialisation, JL101's domain, not a thread mutex)."""
    while node in parents:
        node = parents[node]
        if isinstance(node, ast.With):
            for item in node.items:
                if _is_lockish(ast.unparse(item.context_expr)):
                    return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
    return False


# ---- JL101: blocking calls inside async def ---------------------------------


def _check_blocking_in_async(src: Source, out: list[Finding]) -> None:
    for fn in ast.walk(src.tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        # walk the async body but stay out of nested function bodies:
        # a nested sync def runs only when called, and a nested async
        # def gets its own visit
        stack: list[ast.AST] = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(node, ast.Call):
                name = _blocking_call_name(node)
                if name:
                    out.append(
                        Finding(
                            "JL101", src.rel, node.lineno,
                            f"blocking call `{name}` inside `async def "
                            f"{fn.name}` — the event loop stalls for its "
                            "duration; dispatch via asyncio.to_thread",
                            src.line_src(node.lineno),
                        )
                    )
            stack.extend(ast.iter_child_nodes(node))


# ---- JL102: shared attributes without a declared guard ----------------------


def _thread_entry_names(cls: ast.ClassDef) -> set[str]:
    """Methods handed to threading.Thread(target=self.X) or
    asyncio.to_thread(self.X, ...) / run_in_executor(None, self.X)."""
    entries: set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        cands: list[ast.AST] = []
        if name.endswith("Thread"):
            cands += [kw.value for kw in node.keywords if kw.arg == "target"]
        elif name.endswith("to_thread"):
            cands += node.args[:1]
        elif name.endswith("run_in_executor"):
            cands += node.args[1:2]
        for c in cands:
            attr = _self_attr(c)
            if attr:
                entries.add(attr)
    return entries


def _method_calls(fn: ast.AST) -> set[str]:
    calls = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            attr = _self_attr(node.func)
            if attr:
                calls.add(attr)
    return calls


def _check_shared_attrs(src: Source, out: list[Finding]) -> None:
    parents = parent_map(src.tree)
    for cls in ast.walk(src.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        methods = {
            m.name: m
            for m in cls.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        roots = _thread_entry_names(cls) & set(methods)
        if not roots:
            continue
        # close thread-entry methods over their self-method call graph
        threaded = set(roots)
        frontier = list(roots)
        while frontier:
            m = frontier.pop()
            for callee in _method_calls(methods[m]) & set(methods):
                if callee not in threaded:
                    threaded.add(callee)
                    frontier.append(callee)
        loop_side = set(methods) - threaded - {"__init__"}

        # attr -> {method: [store nodes]}
        stores: dict[str, dict[str, list[ast.AST]]] = {}
        for mname, m in methods.items():
            for node in ast.walk(m):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for t in targets:
                    attr = _self_attr(t)
                    if attr:
                        stores.setdefault(attr, {}).setdefault(mname, []).append(node)

        for attr, per_method in stores.items():
            in_thread = [m for m in per_method if m in threaded]
            in_loop = [m for m in per_method if m in loop_side]
            if not in_thread or not in_loop:
                continue
            for mname in in_thread + in_loop:
                for node in per_method[mname]:
                    if _under_lock_with(node, parents):
                        continue
                    out.append(
                        Finding(
                            "JL102", src.rel, node.lineno,
                            f"`self.{attr}` is mutated from thread method(s) "
                            f"{sorted(in_thread)} AND loop-side method(s) "
                            f"{sorted(in_loop)}; this store in `{mname}` has "
                            "no lock/Condition guard — guard it or declare "
                            "the ownership protocol with `# jlint: shared-ok`",
                            src.line_src(node.lineno),
                        )
                    )


# ---- JL103: read-modify-write spanning an await -----------------------------


def _ordered_nodes(fn: ast.AST) -> list[ast.AST]:
    nodes = [
        n for n in ast.walk(fn)
        if hasattr(n, "lineno") and not isinstance(n, (ast.FunctionDef, ast.Lambda))
    ]
    nodes.sort(key=lambda n: (n.lineno, n.col_offset))
    return nodes


def _check_rmw_across_await(src: Source, out: list[Finding]) -> None:
    for fn in ast.walk(src.tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        await_lines = sorted(
            n.lineno for n in ast.walk(fn) if isinstance(n, ast.Await)
        )
        if not await_lines:
            continue
        # (a) one statement both reads and writes self.X around an await:
        #     `self.x += await f()` / `self.x = self.x + await f()`
        for node in ast.walk(fn):
            is_aug = isinstance(node, ast.AugAssign) and _self_attr(node.target)
            reads_self = False
            attr = None
            if is_aug:
                attr = _self_attr(node.target)
                reads_self = True
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    a = _self_attr(t)
                    if a and any(
                        _self_attr(v) == a for v in ast.walk(node.value)
                    ):
                        attr = a
                        reads_self = True
            if not reads_self:
                continue
            if any(isinstance(v, ast.Await) for v in ast.walk(node.value)):
                out.append(
                    Finding(
                        "JL103", src.rel, node.lineno,
                        f"read-modify-write of `self.{attr}` spans the "
                        "`await` inside its own right-hand side — another "
                        "coroutine can interleave between the read and "
                        "the store",
                        src.line_src(node.lineno),
                    )
                )
        # (b) tmp = self.x ... await ... self.x = f(tmp)
        bindings: dict[str, tuple[str, int]] = {}  # var -> (attr, lineno)
        for node in _ordered_nodes(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    read = _self_attr(node.value)
                    if read:
                        bindings[t.id] = (read, node.lineno)
                    else:
                        bindings.pop(t.id, None)
                attr = _self_attr(t)
                if attr:
                    used = {
                        v.id for v in ast.walk(node.value)
                        if isinstance(v, ast.Name)
                    }
                    for var in used:
                        if var not in bindings:
                            continue
                        bound_attr, bound_line = bindings[var]
                        if bound_attr != attr:
                            continue
                        if any(
                            bound_line < aw <= node.lineno
                            for aw in await_lines
                        ):
                            out.append(
                                Finding(
                                    "JL103", src.rel, node.lineno,
                                    f"`self.{attr}` was read into `{var}` at "
                                    f"line {bound_line}, an `await` ran, and "
                                    "this store writes a value derived from "
                                    "the stale read",
                                    src.line_src(node.lineno),
                                )
                            )


# ---- JL104: blocking I/O while holding a thread lock ------------------------


def _check_lock_io(src: Source, out: list[Finding]) -> None:
    parents = parent_map(src.tree)
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        hit = name in LOCK_IO_CALLS or name in BLOCKING_BUILTINS
        if not hit and isinstance(node.func, ast.Attribute):
            hit = node.func.attr in LOCK_IO_METHOD_NAMES
        if not hit:
            continue
        if _under_lock_with(node, parents):
            out.append(
                Finding(
                    "JL104", src.rel, node.lineno,
                    f"blocking disk I/O `{name or node.func.attr}` while "
                    "holding a thread lock/condition — every other thread "
                    "(the event loop included) blocks behind the disk; move "
                    "the I/O outside the lock or declare the protocol",
                    src.line_src(node.lineno),
                )
            )


# ---- JL001: broad excepts ---------------------------------------------------


def _check_broad_except(src: Source, out: list[Finding]) -> None:
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        broad = node.type is None or (
            isinstance(node.type, ast.Name)
            and node.type.id in ("Exception", "BaseException")
        )
        if broad:
            what = "bare except" if node.type is None else f"except {node.type.id}"
            out.append(
                Finding(
                    "JL001", src.rel, node.lineno,
                    f"{what} — narrow to the concrete exception(s) or justify "
                    "with `# jlint: broad-ok` (and log what was swallowed)",
                    src.line_src(node.lineno),
                )
            )


def run(sources: list[Source]) -> list[Finding]:
    out: list[Finding] = []
    for src in sources:
        _check_blocking_in_async(src, out)
        _check_shared_attrs(src, out)
        _check_rmw_across_await(src, out)
        _check_lock_io(src, out)
        _check_broad_except(src, out)
    return out


def run_interprocedural(project) -> list[Finding]:
    """JL101 beyond the enclosing function (the jlint-v2 upgrade): a
    call inside an ``async def`` whose resolved SYNC callee transitively
    reaches a blocking primitive stalls the loop just like a direct
    ``os.fsync`` — the syntactic walk above cannot see it (JL104's
    journal-rotation stall was the same shape one domain over). Uses the
    core's no-false-edge call graph, so every finding names the chain."""
    closure = project.blocking_closure()
    out: list[Finding] = []
    for fi in project.functions.values():
        if not fi.is_async:
            continue
        src = project.by_rel.get(fi.rel)
        direct_lines = {line for _n, line, _l in fi.blocking}
        for site in fi.calls:
            if site.lineno in direct_lines:
                continue  # the syntactic JL101 already owns this line
            for t in site.targets:
                chain = closure.get(t)
                callee = project.functions.get(t)
                if chain is None or callee is None or callee.is_async:
                    continue
                out.append(
                    Finding(
                        "JL101", fi.rel, site.lineno,
                        f"call `{site.raw}` inside `async def {fi.name}` "
                        f"reaches blocking `{chain[-1]}` via "
                        f"{' -> '.join(chain)} — the event loop stalls "
                        "for its duration; dispatch via asyncio.to_thread",
                        src.line_src(site.lineno) if src is not None else "",
                    )
                )
                break
    return out
