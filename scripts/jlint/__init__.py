"""jlint: the repo-native static analyzer (`make lint`, part of `make ci`).

The repo spans three domains where bugs are silent until they cost a
re-record, and each gets a dedicated analysis pass:

* **Pass 1 — async/thread safety** (`pass_async`, rules JL1xx): the
  asyncio serving loop shares state with the journal writer thread and
  with `asyncio.to_thread` drains. Blocking calls on the loop, shared
  attributes mutated from both sides without a declared guard,
  read-modify-write sequences spanning an ``await``, and blocking disk
  I/O performed while holding a thread lock are all flagged.
* **Pass 2 — JAX trace discipline** (`pass_jax`, rules JL2xx) over
  ``jylis_tpu/ops/``: host syncs reachable from ``@jax.jit`` functions,
  data-dependent Python branching on traced values, dtype-implicit
  array constructors outside the documented x64 guards, and jit
  construction inside hot functions (per-call recompilation).
* **Pass 3 — RESP surface parity** (`pass_parity`, rules JL3xx): the
  native engine's command dispatch (``native/serve_engine.cpp``) is
  extracted alongside the Python oracle dispatch (``models/repo_*.py``)
  into a committed parity manifest; a command served natively without a
  Python oracle path fails, and any drift between the sources and the
  committed manifest fails — PR 2's hand-checked parity as a mechanical
  invariant.
* **Pass 4 — failpoint manifest parity** (`pass_failpoints`, rules
  JL4xx): every ``faults.point(...)`` name in the product tree must be
  a string literal declared in the committed
  ``scripts/jlint/failpoints_manifest.json`` with a one-line
  description; undeclared, stale, or undescribed names fail, so the
  set of injectable failure seams stays reviewed and documented.
* **Pass 5 — metrics manifest parity** (`pass_metrics`, rules JL5xx):
  every histogram/gauge/trace-event name in the observability layer
  (``.hist()`` / ``.gauge_set()`` / ``.trace_event()`` /
  ``timed_drain()`` call sites) must be a string literal declared in
  the committed ``scripts/jlint/metrics_manifest.json`` AND
  pre-registered in ``jylis_tpu/obs/__init__.py``; stale entries and
  dead declarations fail, so the scrapeable surface stays reviewed.

Plus one hygiene rule, JL001: ``except Exception`` / bare ``except``
without an explicit justification, so hot-path errors can't be silently
swallowed.

Suppression works at two levels, both requiring a human-readable reason:

* inline: a ``# jlint: <slug>`` comment on the flagged line or the line
  above (slugs per rule in ``RULES``; e.g. ``# jlint: shared-ok —
  writer-owns-file protocol``);
* the committed baseline (``scripts/jlint/baseline.json``): entries of
  ``{"rule", "file", "match", "reason"}`` where ``match`` must appear in
  the flagged source line. A baseline entry that no longer matches any
  finding is STALE and fails the run, so suppressions can't outlive the
  code they excuse.

Run ``python -m scripts.jlint`` from the repo root (what ``make lint``
does); ``--write-manifest`` regenerates the pass-3 parity manifest.
"""

from __future__ import annotations

import ast
import json
import os
import tokenize
from dataclasses import dataclass, field

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline.json")
MANIFEST_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "parity_manifest.json"
)

# rule id -> (inline suppression slug, one-line description)
RULES = {
    "JL001": ("broad-ok", "broad `except Exception`/bare except without justification"),
    "JL101": ("blocking-ok", "known-blocking call inside `async def` without executor dispatch"),
    "JL102": ("shared-ok", "attribute mutated from both a worker thread and the event loop without a declared guard"),
    "JL103": ("rmw-ok", "read-modify-write of a shared attribute spanning an `await`"),
    "JL104": ("lockio-ok", "blocking disk I/O while holding a thread lock/condition"),
    "JL201": ("hostsync-ok", "host sync (.item()/float()/np.asarray) reachable from a @jax.jit function"),
    "JL202": ("branch-ok", "data-dependent Python branch on a traced value inside a jit function"),
    "JL203": ("dtype-ok", "dtype-implicit array constructor in jit code outside an x64 guard"),
    "JL204": ("jit-ok", "jax.jit constructed inside a function body (per-call recompilation)"),
    "JL301": (None, "command served natively without a Python oracle path (or vice versa, unlisted)"),
    "JL302": (None, "parity manifest drift: committed manifest != extracted surfaces"),
    "JL401": (None, "failpoint name non-literal or not declared in failpoints_manifest.json"),
    "JL402": (None, "failpoints manifest entry stale, missing, or undescribed"),
    "JL501": (None, "metric name non-literal, not declared in metrics_manifest.json, or not pre-registered in obs"),
    "JL502": (None, "metrics manifest / obs declaration stale, missing, or undescribed"),
    "JL601": ("lane-shared-ok", "module-level mutable (per-LANE state under --lanes N) not declared in lanes_manifest.json"),
    "JL602": (None, "lanes manifest entry stale, missing, or undescribed"),
    "JL900": (None, "stale or malformed baseline suppression entry"),
}


@dataclass
class Finding:
    rule: str
    path: str  # repo-relative
    line: int
    msg: str
    src: str = ""  # stripped source line, what baseline `match` runs against
    suppressed: bool = False
    baseline: bool = False

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.msg}"


@dataclass
class Source:
    """One parsed Python file plus the comment map suppressions need."""

    path: str  # absolute
    rel: str  # repo-relative
    text: str
    tree: ast.AST
    lines: list[str] = field(default_factory=list)
    comments: dict[int, str] = field(default_factory=dict)  # line -> comment text

    @classmethod
    def load(cls, path: str, root: str = ROOT) -> "Source":
        with open(path, encoding="utf-8") as f:
            text = f.read()
        tree = ast.parse(text, filename=path)
        src = cls(
            path=path,
            rel=os.path.relpath(path, root),
            text=text,
            tree=tree,
            lines=text.splitlines(),
        )
        # tokenize for comments: `# jlint: slug` anywhere in a comment
        try:
            for tok in tokenize.generate_tokens(iter(text.splitlines(True)).__next__):
                if tok.type == tokenize.COMMENT:
                    src.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:
            pass
        return src

    def line_src(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def has_suppression(self, lineno: int, slug: str) -> bool:
        """`# jlint: <slug>` on the line, or on the line above it."""
        for ln in (lineno, lineno - 1):
            c = self.comments.get(ln, "")
            if "jlint:" in c and slug in c.split("jlint:", 1)[1]:
                return True
        return False


def parent_map(tree: ast.AST) -> dict[ast.AST, ast.AST]:
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def dotted_name(node: ast.AST) -> str:
    """'os.fsync' for Attribute chains, 'open' for Names, '' otherwise."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif parts:
        parts.append("?")  # call on a computed receiver: keep the tail
    return ".".join(reversed(parts))


def iter_py_files(root: str, subdirs: tuple[str, ...]) -> list[str]:
    out = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for f in sorted(filenames):
                if f.endswith(".py"):
                    out.append(os.path.join(dirpath, f))
    return sorted(out)


def apply_suppressions(findings: list[Finding], sources: dict[str, "Source"]) -> None:
    """Mark findings carrying a matching inline `# jlint: <slug>` comment."""
    for f in findings:
        slug = RULES[f.rule][0]
        src = sources.get(f.path)
        if slug and src is not None and src.has_suppression(f.line, slug):
            f.suppressed = True


def load_baseline(path: str = BASELINE_PATH) -> list[dict]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def apply_baseline(
    findings: list[Finding], baseline: list[dict]
) -> list[Finding]:
    """Suppress findings matched by baseline entries; return JL900
    findings for entries that are malformed or match nothing (stale)."""
    problems: list[Finding] = []
    for i, entry in enumerate(baseline):
        rule = entry.get("rule", "")
        file_ = entry.get("file", "")
        match = entry.get("match", "")
        reason = entry.get("reason", "")
        if not (rule and file_ and match) or not reason.strip():
            problems.append(
                Finding(
                    "JL900", BASELINE_PATH_REL, i + 1,
                    f"baseline entry {i} malformed or missing a reason: {entry!r}",
                )
            )
            continue
        hit = False
        for f in findings:
            if (
                f.rule == rule
                and f.path == file_
                and match in f.src
                and not f.suppressed
            ):
                f.suppressed = True
                f.baseline = True
                hit = True
        if not hit:
            problems.append(
                Finding(
                    "JL900", BASELINE_PATH_REL, i + 1,
                    f"stale baseline entry {i}: no current {rule} finding in "
                    f"{file_} matches {match!r} — delete the entry",
                )
            )
    return problems


BASELINE_PATH_REL = os.path.relpath(BASELINE_PATH, ROOT)
