"""jlint: the repo-native static analyzer (`make lint`, part of `make ci`).

The repo spans three domains where bugs are silent until they cost a
re-record, and each gets a dedicated analysis pass:

* **Pass 1 — async/thread safety** (`pass_async`, rules JL1xx): the
  asyncio serving loop shares state with the journal writer thread and
  with `asyncio.to_thread` drains. Blocking calls on the loop, shared
  attributes mutated from both sides without a declared guard,
  read-modify-write sequences spanning an ``await``, and blocking disk
  I/O performed while holding a thread lock are all flagged.
* **Pass 2 — JAX trace discipline** (`pass_jax`, rules JL2xx) over
  ``jylis_tpu/ops/``: host syncs reachable from ``@jax.jit`` functions,
  data-dependent Python branching on traced values, dtype-implicit
  array constructors outside the documented x64 guards, and jit
  construction inside hot functions (per-call recompilation).
* **Pass 3 — RESP surface parity** (`pass_parity`, rules JL3xx): the
  native engine's command dispatch (``native/serve_engine.cpp``) is
  extracted alongside the Python oracle dispatch (``models/repo_*.py``)
  into a committed parity manifest; a command served natively without a
  Python oracle path fails, and any drift between the sources and the
  committed manifest fails — PR 2's hand-checked parity as a mechanical
  invariant.
* **Pass 4 — failpoint manifest parity** (`pass_failpoints`, rules
  JL4xx): every ``faults.point(...)`` name in the product tree must be
  a string literal declared in the committed
  ``scripts/jlint/failpoints_manifest.json`` with a one-line
  description; undeclared, stale, or undescribed names fail, so the
  set of injectable failure seams stays reviewed and documented.
* **Pass 5 — metrics manifest parity** (`pass_metrics`, rules JL5xx):
  every histogram/gauge/trace-event name in the observability layer
  (``.hist()`` / ``.gauge_set()`` / ``.trace_event()`` /
  ``timed_drain()`` call sites) must be a string literal declared in
  the committed ``scripts/jlint/metrics_manifest.json`` AND
  pre-registered in ``jylis_tpu/obs/__init__.py``; stale entries and
  dead declarations fail, so the scrapeable surface stays reviewed.
* **Pass 6 — cross-lane shared-state discipline** (`pass_lanes`, rules
  JL6xx): every module-level mutable in ``jylis_tpu/`` is per-LANE
  state under ``--lanes N`` and must be declared in the committed
  ``lanes_manifest.json`` with why per-process copies are correct.

jlint v2 adds a shared INTERPROCEDURAL core (``core.py`` +
``graph.py``: per-project module/call graph with no-false-edge
resolution, per-function held-locks/blocking/await summaries,
content-hash-cached ASTs) that upgrades pass 1's JL101 to see blocking
calls through the call graph and powers three semantic passes:

* **Pass 7 — codec round-trip symmetry** (`pass_codec`, JL70x): the
  paired encoders/decoders of all three wire/disk formats extract to
  field-sequence tokens committed in ``codec_manifest.json``; order/
  width/endianness drift, unconsumed fields, over-reads, and manifest
  drift fail. The manifest drives the golden corpus
  (``tests/golden/codec_corpus.json``, ``--write-corpus``).
* **Pass 8 — CRDT lattice-law discipline** (`pass_lattice`, JL80x):
  wall-clock reads reachable from merge/join/apply paths, unordered
  iteration feeding digests/wire/flushes, delta mutation after sink
  aliasing, replica-id branches in joins; ``lattice_manifest.json``
  documents each obligation and GENERATES the dynamic property harness
  (``tests/test_lattice_laws.py``: join commutativity/associativity/
  idempotence over seeded random deltas for all five types).
* **Pass 9 — cross-thread lock order** (`pass_locks`, JL90x): await
  while holding a threading lock, lock-acquisition cycles over the
  global lock graph, and blocking I/O reachable under a held lock
  interprocedurally (the case pass 1's syntactic JL104 missed).
* **Pass 10 — protocol atlas** (`pass_protocol`, JL100x): the full
  transition relation of the cluster protocol — (role, state, message)
  → permitted effects (sends, converges, state mutations, teardown
  reasons, metric/trace emissions) — extracted from ``cluster.py``'s
  handler dispatch, handshake, sync machinery and dial state machine
  into the committed ``protocol_manifest.json``. Undeclared effects,
  silent fall-throughs, and manifest drift fail; jmodel
  (``scripts/jmodel``) explores the same protocol dynamically.

jlint v3 adds the cross-language seam:

* **Pass 11 — RESP semantic parity** (`pass_semantics`, JL110x): a
  purpose-built C++ front-end (``cpp_ast.py`` — tokenizer + recursive
  descent over the disciplined subset ``native/`` is written in, no
  libclang) symbolically extracts every natively-served command's
  argument grammar, numeric bounds, validators, reply shape and error
  mode from ``native/serve_engine.cpp``/``resp_parser.cpp``/
  ``engine.h``, diffs them against the Python oracle's dispatch ASTs
  into the committed ``semantics_manifest.json`` (JL1101 grammar/
  bounds/transport/threshold divergence, JL1102 reply-shape/error
  divergence, JL1103 drift/stale/placeholder/coverage/stale-harness),
  and GENERATES the differential fuzz harness
  (``tests/test_semantic_fuzz.py`` via ``scripts/gen_semfuzz.py``:
  seeded valid/boundary/mutated-invalid command streams byte-compared
  through both server paths, corpus sha-pinned in ``tests/golden/``).

Plus the hygiene rules: JL001 (``except Exception`` / bare ``except``
without justification), JL002 (an inline suppression carrying no
reason), JL003 (a stale inline suppression whose rule no longer fires
at that site), and JL000 (stale/malformed baseline entries).

Suppression works at two levels, both requiring a human-readable reason:

* inline: a ``# jlint: <slug>`` comment on the flagged line, or
  anywhere in the contiguous comment block directly above it (slugs
  per rule in ``RULES``; e.g. ``# jlint: shared-ok —
  writer-owns-file protocol``). Reason-less markers fail (JL002);
  markers whose rule no longer fires at the site fail (JL003);
* the committed baseline (``scripts/jlint/baseline.json``): entries of
  ``{"rule", "file", "match", "reason"}`` where ``match`` must appear in
  the flagged source line. A baseline entry that no longer matches any
  finding is STALE and fails the run, so suppressions can't outlive the
  code they excuse.

Run ``python -m scripts.jlint`` from the repo root (what ``make lint``
does, plus ``--budget --out lint_findings.json``); ``--write-manifest``
regenerates every committed manifest and the generated lattice +
semantic-fuzz harnesses, ``--write-corpus`` re-records the golden
codec and semantic-fuzz corpora.
"""

from __future__ import annotations

import ast
import json
import os
import tokenize
from dataclasses import dataclass, field

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline.json")
MANIFEST_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "parity_manifest.json"
)

# rule id -> (inline suppression slug, one-line description)
RULES = {
    "JL000": (None, "stale or malformed baseline suppression entry"),
    "JL002": (None, "inline `# jlint:` suppression carries no reason"),
    "JL003": (None, "stale inline suppression: its rule no longer fires at that line"),
    "JL001": ("broad-ok", "broad `except Exception`/bare except without justification"),
    "JL101": ("blocking-ok", "known-blocking call inside `async def` without executor dispatch"),
    "JL102": ("shared-ok", "attribute mutated from both a worker thread and the event loop without a declared guard"),
    "JL103": ("rmw-ok", "read-modify-write of a shared attribute spanning an `await`"),
    "JL104": ("lockio-ok", "blocking disk I/O while holding a thread lock/condition"),
    "JL201": ("hostsync-ok", "host sync (.item()/float()/np.asarray) reachable from a @jax.jit function"),
    "JL202": ("branch-ok", "data-dependent Python branch on a traced value inside a jit function"),
    "JL203": ("dtype-ok", "dtype-implicit array constructor in jit code outside an x64 guard"),
    "JL204": ("jit-ok", "jax.jit constructed inside a function body (per-call recompilation)"),
    "JL301": (None, "command served natively without a Python oracle path (or vice versa, unlisted)"),
    "JL302": (None, "parity manifest drift: committed manifest != extracted surfaces"),
    "JL401": (None, "failpoint name non-literal or not declared in failpoints_manifest.json"),
    "JL402": (None, "failpoints manifest entry stale, missing, or undescribed"),
    "JL501": (None, "metric name non-literal, not declared in metrics_manifest.json, or not pre-registered in obs"),
    "JL502": (None, "metrics manifest / obs declaration stale, missing, or undescribed"),
    "JL601": ("lane-shared-ok", "module-level mutable (per-LANE state under --lanes N) not declared in lanes_manifest.json"),
    "JL602": (None, "lanes manifest entry stale, missing, or undescribed"),
    "JL701": (None, "codec encoder/decoder field sequences diverge (order/width/endianness drift)"),
    "JL702": (None, "codec field written but never consumed, or decoder reads past the wire shape"),
    "JL703": (None, "codec manifest drift or missing (--write-manifest regenerates)"),
    "JL801": ("wallclock-ok", "wall-clock read reachable from a merge/join/apply path"),
    "JL802": ("order-ok", "unordered dict/set iteration feeding a digest, wire encoding, or flush export"),
    "JL803": ("alias-ok", "delta/batch mutated in place after aliasing into a journal/broadcast/held sink"),
    "JL804": ("ridbranch-ok", "replica-id-dependent branch inside a join path"),
    "JL805": (None, "lattice manifest or generated property harness stale, missing, or undescribed"),
    "JL901": ("awaitlock-ok", "`await` while holding a threading lock"),
    "JL902": (None, "lock-acquisition cycle across the thread/loop seams (potential deadlock)"),
    "JL903": ("lockio-ok", "blocking call reachable under a held lock through the call graph"),
    "JL1001": (None, "cluster protocol handler effect outside the committed atlas (protocol_manifest.json)"),
    "JL1002": (None, "undeclared (role, state, msg) fall-through or silent ignore in a cluster protocol handler"),
    "JL1003": (None, "protocol manifest drift, missing, or undescribed (--write-manifest regenerates)"),
    "JL1101": (None, "native command grammar/bounds diverge from the Python oracle (arity, u64 args, transport limits, thresholds)"),
    "JL1102": (None, "native RESP reply shape or error taxonomy diverges from the Python oracle"),
    "JL1103": (None, "semantics manifest drift/stale/placeholder, uncovered native command, or stale generated fuzz harness"),
}

# slug -> every rule that honors it (JL104/JL903 share lockio-ok; the
# inline-staleness check JL003 treats a suppression as live when ANY of
# its slug's rules fires at the site)
SLUG_RULES: dict[str, set[str]] = {}
for _rule, (_slug, _desc) in RULES.items():
    if _slug:
        SLUG_RULES.setdefault(_slug, set()).add(_rule)


@dataclass
class Finding:
    rule: str
    path: str  # repo-relative
    line: int
    msg: str
    src: str = ""  # stripped source line, what baseline `match` runs against
    suppressed: bool = False
    baseline: bool = False

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.msg}"


@dataclass
class Source:
    """One parsed Python file plus the comment map suppressions need."""

    path: str  # absolute
    rel: str  # repo-relative
    text: str
    tree: ast.AST
    lines: list[str] = field(default_factory=list)
    comments: dict[int, str] = field(default_factory=dict)  # line -> comment text

    @classmethod
    def load(cls, path: str, root: str = ROOT, tree: ast.AST | None = None) -> "Source":
        """Parse `path` (or adopt a pre-parsed `tree` — the core's
        content-hash AST cache passes one) into a Source. ONE
        construction path: field additions and comment-scan rules live
        here only."""
        with open(path, encoding="utf-8") as f:
            text = f.read()
        if tree is None:
            tree = ast.parse(text, filename=path)
        src = cls(
            path=path,
            rel=os.path.relpath(path, root),
            text=text,
            tree=tree,
            lines=text.splitlines(),
        )
        # tokenize for comments: `# jlint: slug` anywhere in a comment
        try:
            for tok in tokenize.generate_tokens(iter(text.splitlines(True)).__next__):
                if tok.type == tokenize.COMMENT:
                    src.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:
            pass
        return src

    def line_src(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def _is_comment_line(self, lineno: int) -> bool:
        """True when the line holds nothing but a comment."""
        return lineno in self.comments and self.line_src(lineno).startswith("#")

    def has_suppression(self, lineno: int, slug: str) -> bool:
        """`# jlint: <slug>` on the line itself, or anywhere in the
        contiguous comment block directly above it (multi-line
        justifications are encouraged, not penalised). The slug is
        matched as an exact token — the same parse the JL002/JL003
        hygiene uses — so a typo'd slug never suppresses by substring
        while being invisible to the staleness check."""
        if comment_slug(self.comments.get(lineno, "")) == slug:
            return True
        ln = lineno - 1
        while ln >= 1 and self._is_comment_line(ln):
            if comment_slug(self.comments.get(ln, "")) == slug:
                return True
            ln -= 1
        return False

    def suppression_target(self, lineno: int) -> int:
        """The code line a suppression comment at `lineno` covers: the
        line itself when the comment rides code, else the first code
        line below the comment block."""
        if not self._is_comment_line(lineno):
            return lineno
        ln = lineno + 1
        while ln <= len(self.lines) and self._is_comment_line(ln):
            ln += 1
        return ln


def parent_map(tree: ast.AST) -> dict[ast.AST, ast.AST]:
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def dotted_name(node: ast.AST) -> str:
    """'os.fsync' for Attribute chains, 'open' for Names, '' otherwise."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif parts:
        parts.append("?")  # call on a computed receiver: keep the tail
    return ".".join(reversed(parts))


def iter_py_files(root: str, subdirs: tuple[str, ...]) -> list[str]:
    out = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for f in sorted(filenames):
                if f.endswith(".py"):
                    out.append(os.path.join(dirpath, f))
    return sorted(out)


def apply_suppressions(findings: list[Finding], sources: dict[str, "Source"]) -> None:
    """Mark findings carrying a matching inline `# jlint: <slug>` comment."""
    for f in findings:
        slug = RULES[f.rule][0]
        src = sources.get(f.path)
        if slug and src is not None and src.has_suppression(f.line, slug):
            f.suppressed = True


def load_baseline(path: str = BASELINE_PATH) -> list[dict]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def apply_baseline(
    findings: list[Finding], baseline: list[dict]
) -> list[Finding]:
    """Suppress findings matched by baseline entries; return JL900
    findings for entries that are malformed or match nothing (stale)."""
    problems: list[Finding] = []
    for i, entry in enumerate(baseline):
        rule = entry.get("rule", "")
        file_ = entry.get("file", "")
        match = entry.get("match", "")
        reason = entry.get("reason", "")
        if not (rule and file_ and match) or not reason.strip():
            problems.append(
                Finding(
                    "JL000", BASELINE_PATH_REL, i + 1,
                    f"baseline entry {i} malformed or missing a reason: {entry!r}",
                )
            )
            continue
        hit = False
        for f in findings:
            if (
                f.rule == rule
                and f.path == file_
                and match in f.src
                and not f.suppressed
            ):
                f.suppressed = True
                f.baseline = True
                hit = True
        if not hit:
            problems.append(
                Finding(
                    "JL000", BASELINE_PATH_REL, i + 1,
                    f"stale baseline entry {i}: no current {rule} finding in "
                    f"{file_} matches {match!r} — delete the entry",
                )
            )
    return problems


BASELINE_PATH_REL = os.path.relpath(BASELINE_PATH, ROOT)


def comment_slug(comment: str) -> str | None:
    """The exact `jlint: <slug>` token in a comment, or None. One
    parser for suppression matching AND the JL002/JL003 hygiene, so a
    slug that suppresses is always one the hygiene can see."""
    if "jlint:" not in comment:
        return None
    after = comment.split("jlint:", 1)[1].strip()
    slug = ""
    for ch in after:
        if ch.isalnum() or ch == "-":
            slug += ch
        else:
            break
    return slug or None


def _suppression_sites(src: "Source"):
    """(line, slug, reason) for every `# jlint: <slug>` comment. The
    reason is whatever explanatory text the comment carries besides the
    marker itself — before it (`# boot path — jlint: lockio-ok`) or
    after it (`# jlint: shared-ok (caller holds _cv)`)."""
    for line, comment in sorted(src.comments.items()):
        slug = comment_slug(comment)
        if slug is None or slug not in SLUG_RULES:
            continue
        before, after = comment.split("jlint:", 1)
        after = after.strip()
        reason = (before.lstrip("#").strip() + " " + after[len(slug):].strip()).strip()
        yield line, slug, reason


def check_inline_suppressions(
    all_findings: list[Finding], sources: dict[str, "Source"]
) -> list[Finding]:
    """Inline-suppression hygiene (the baseline-staleness discipline
    extended to inline sites): every `# jlint: <slug>` must carry a
    reason (JL002), and must still have a matching finding on its line
    or the line below (JL003 — a suppression outliving the code it
    excused is deleted, not inherited by whatever lands there next)."""
    # (rel, line, rule) for every PRE-suppression finding
    fired: set[tuple[str, int, str]] = {
        (f.rule, f.path, f.line) for f in all_findings
    }
    out: list[Finding] = []
    for rel, src in sorted(sources.items()):
        for line, slug, reason in _suppression_sites(src):
            if len([c for c in reason if c.isalpha()]) < 4:
                out.append(
                    Finding(
                        "JL002", rel, line,
                        f"inline suppression `jlint: {slug}` carries no "
                        "reason — say WHY the rule does not apply here "
                        "(e.g. `# jlint: "
                        f"{slug} — writer-owns-file protocol`)",
                        src.line_src(line),
                    )
                )
            target = src.suppression_target(line)
            live = any(
                (rule, rel, ln) in fired
                for rule in SLUG_RULES[slug]
                for ln in (line, target)
            )
            if not live:
                out.append(
                    Finding(
                        "JL003", rel, line,
                        f"stale inline suppression `jlint: {slug}`: no "
                        f"{'/'.join(sorted(SLUG_RULES[slug]))} finding "
                        "fires at this line any more — delete the comment",
                        src.line_src(line),
                    )
                )
    return out
