"""Pass 9 — cross-thread lock-order analysis (rules JL901/JL902/JL903).

The node is one asyncio loop + a journal writer thread + to_thread
drain workers + lane worker processes, coordinating through a handful
of threading locks and condition variables. The three failure shapes
this pass mechanises are the ones reviews kept having to re-derive by
hand from multi-function context:

* **JL901 — await while holding a threading lock**: a sync ``with
  <lock>`` whose body awaits parks the COROUTINE but not the lock; any
  thread (and any other coroutine reaching the same lock) deadlocks or
  stalls behind a suspended owner. (An ``async with`` is the loop's own
  serialisation and is fine.)
* **JL902 — lock-acquisition cycle**: the global lock graph — an edge
  A→B whenever B is acquired while A is held, in one function or
  through any resolved call chain — must be acyclic, across the
  thread/loop/lane seams. A cycle is a potential deadlock the drill
  matrix can only hit probabilistically; here it is structural.
  Lock identity is class-scoped (``Journal._cv``); acquiring the SAME
  attribute on several instances (the ordered ``Database.all_locks``
  pattern) is a self-edge and deliberately ignored — instance order is
  not statically visible.
* **JL903 — blocking I/O reachable under a held lock,
  interprocedurally**: pass 1's JL104 sees only the syntactically
  enclosing function (the journal-rotation stall it missed, PR 3's
  JL104 fix, was exactly a callee doing the fsync). This walks the
  blocking closure from every call made with a lock held: fsync /
  rename / open / sleep two frames down still serialises every other
  thread behind the disk.

All three consume the core's held-locks/call summaries
(scripts/jlint/core.py); resolution follows graph.py's no-false-edge
discipline, so every finding names a concrete witness chain.
"""

from __future__ import annotations

from . import Finding


def check_await_under_lock(project) -> list[Finding]:
    out: list[Finding] = []
    for fi in project.functions.values():
        if not fi.is_async:
            continue
        src = project.by_rel.get(fi.rel)
        for lineno, locks in fi.awaits:
            if locks:
                out.append(
                    Finding(
                        "JL901", fi.rel, lineno,
                        f"`await` while holding threading lock(s) "
                        f"{sorted(set(locks))} in `{fi.name}` — the "
                        "coroutine parks but the lock stays held; every "
                        "thread (and coroutine) behind it stalls until "
                        "this coroutine is resumed",
                        src.line_src(lineno) if src is not None else "",
                    )
                )
    return out


def check_lock_cycles(project) -> list[Finding]:
    edges = project.lock_edges()
    # adjacency over named locks; self-edges (same class attribute,
    # different instances) are excluded by lock_edges already
    adj: dict[str, set[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)
    out: list[Finding] = []
    seen_cycles: set[tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: list[str], visited: set[str]):
        for nxt in sorted(adj.get(node, ())):
            if nxt == start and len(path) > 1:
                cyc = tuple(sorted(path))
                if cyc in seen_cycles:
                    continue
                seen_cycles.add(cyc)
                rel, line, via = edges[(path[-1], start)]
                out.append(
                    Finding(
                        "JL902", rel, line,
                        "lock-acquisition cycle: "
                        + " -> ".join(path + [start])
                        + f" (edge witnessed {via}) — a potential "
                        "deadlock across the thread/loop seams; break "
                        "the cycle or collapse the locks",
                        "",
                    )
                )
            elif nxt not in visited and nxt in adj:
                dfs(start, nxt, path + [nxt], visited | {nxt})

    for start in sorted(adj):
        dfs(start, start, [start], {start})
    return out


def check_blocking_under_lock(project) -> list[Finding]:
    """Interprocedural JL104: a call site with a lock held whose SYNC
    callee closure reaches a blocking primitive."""
    closure = project.blocking_closure()
    out: list[Finding] = []
    for fi in project.functions.values():
        src = project.by_rel.get(fi.rel)
        for site in fi.calls:
            if not site.locks:
                continue
            for t in site.targets:
                chain = closure.get(t)
                if chain is None:
                    continue
                out.append(
                    Finding(
                        "JL903", fi.rel, site.lineno,
                        f"call `{site.raw}` under held lock(s) "
                        f"{sorted(set(site.locks))} reaches blocking "
                        f"`{chain[-1]}` via {' -> '.join(chain)} — every "
                        "other thread (the event loop included) blocks "
                        "behind the I/O; move it outside the lock or "
                        "declare the protocol",
                        src.line_src(site.lineno) if src is not None else "",
                    )
                )
                break  # one finding per call site
    return out


def run(project) -> list[Finding]:
    return (
        check_await_under_lock(project)
        + check_lock_cycles(project)
        + check_blocking_under_lock(project)
    )
