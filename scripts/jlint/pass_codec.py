"""Pass 7 — codec round-trip symmetry (rules JL701/JL702/JL703).

Three wire/disk formats carry every byte this system persists or
gossips: the cluster transport (cluster/codec.py + framing.py +
cluster.py's CRC/origin wire frame), the delta journal
(journal/journal.py), and snapshots (persist.py). Their encoders and
decoders are separate functions whose field order, widths, and
endianness must agree EXACTLY — and until this pass, nothing checked
that statically: an encoder gaining a field whose decoder was not
updated ships as silent corruption detected only when a peer (or a
reboot) reads the bytes. Schema v7 (digest-driven delta sync) and the
native RESP port will both edit these functions; this pass is the rail
they get built under.

Mechanics — two extraction grades:

* **token units** (the cluster codec's message and per-type delta
  shapes): a symbolic evaluator walks the paired encode/decode function
  bodies in Python evaluation order and emits the primitive field
  sequence — ``varint`` / ``bytes`` / ``str`` / ``u8:<tag-const>`` /
  struct widths (``u32be``, ``u64be``…) — expanding helper calls
  (``_w_addr`` ↔ ``_r_addr``, ``read_ujson``) and folding loops and
  comprehensions into ``rep[...]`` groups. Encoder and decoder
  sequences must be identical: a mismatch is JL701 (order / width /
  endianness drift); one side being a strict prefix of the other is
  JL702 (encoder writes a field no decoder consumes, or a decoder
  reads past what the encoder produced).
* **atom units** (framing header, cluster wire frame, journal file,
  snapshot file): the writer and reader are scanned for an ordered
  first-touch sequence over a per-unit vocabulary (struct formats,
  ``MAGIC``, ``delta_signature``, framing, crc, body codec); the
  reader's atom set must cover the writer's exactly (JL702), with the
  loader's legacy-signature acceptance recorded as a flag, not a field.

Everything extracted is committed to ``scripts/jlint/
codec_manifest.json`` keyed by the schema version (plus the legacy
snapshot-signature versions the loader still accepts); any drift
between the committed manifest and the extracted truth fails (JL703 —
``--write-manifest`` regenerates, the git diff is the review surface).
The manifest also drives the golden round-trip corpus
(``tests/golden/codec_corpus.json``, regenerated via
``--write-corpus``): the corpus records the manifest's sha256, so a
schema edit that regenerates the manifest without re-recording the
corpus fails in tier-1.

The native codec wrapper (native/codec.py) is pinned at a coarser
grade: the FFI call argument order per type (the flattened field
layout the C++ side consumes) is recorded in the manifest, so a layout
change is a reviewed manifest diff; byte-level equivalence with the
oracle stays with the existing differential fuzz
(tests/test_native_codec.py).
"""

from __future__ import annotations

import ast
import hashlib
import json
import os

from . import Finding, ROOT, dotted_name

CODEC_MANIFEST_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "codec_manifest.json"
)

CODEC_REL = os.path.join("jylis_tpu", "cluster", "codec.py")
FRAMING_REL = os.path.join("jylis_tpu", "cluster", "framing.py")
CLUSTER_REL = os.path.join("jylis_tpu", "cluster", "cluster.py")
JOURNAL_REL = os.path.join("jylis_tpu", "journal", "journal.py")
PERSIST_REL = os.path.join("jylis_tpu", "persist.py")
UJSON_WIRE_REL = os.path.join("jylis_tpu", "ops", "ujson_wire.py")
NATIVE_CODEC_REL = os.path.join("jylis_tpu", "native", "codec.py")

# message tag constant <-> unit name (both sides must use the constant)
TAG_UNITS = {
    "_TAG_PONG": "Pong",
    "_TAG_EXCHANGE": "ExchangeAddrs",
    "_TAG_ANNOUNCE": "AnnounceAddrs",
    "_TAG_PUSH": "PushDeltas",
    "_TAG_SYNC_REQ": "SyncRequest",
    "_TAG_SYNC_DONE": "SyncDone",
    # schema v8 (anti-entropy v2): delta intervals + Merkle-range repair
    "_TAG_DELTA_ACK": "DeltaAck",
    "_TAG_SEQ_PUSH": "SeqPush",
    "_TAG_DIGEST_TREE": "DigestTree",
    "_TAG_RANGE_REQ": "RangeRequest",
    "_TAG_INTERVAL_RESET": "IntervalReset",
    # schema v10 (sessions & regions): the origin-preserving relay and
    # the region-membership gossip
    "_TAG_RELAY_PUSH": "RelayPush",
    "_TAG_REGION_GOSSIP": "RegionGossip",
}

DELTA_TYPES = (
    "TREG", "TLOG", "SYSTEM", "GCOUNT", "PNCOUNT", "UJSON", "TENSOR",
    "MAP", "BCOUNT",
)

_STRUCT_TOKENS = {"B": "u8", "H": "u16", "I": "u32", "Q": "u64", "i": "i32", "q": "i64"}


class ExtractError(Exception):
    """The codec idiom this extractor understands was not found — fail
    loudly so a refactor cannot silently skate past the symmetry check."""


def _parse(rel: str, root: str = ROOT) -> ast.Module:
    from .core import parse_cached

    path = os.path.join(root, rel)
    with open(path, encoding="utf-8") as f:
        text = f.read()
    return parse_cached(text, path)


def _functions(tree: ast.Module) -> dict[str, ast.AST]:
    return {
        n.name: n
        for n in tree.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


# one dotted-name walker for the whole package (scripts/jlint/__init__)
_dotted = dotted_name


def _struct_tokens(fmt: str) -> list:
    """'>BQ' -> ['u8', 'u64be']; endianness rides the token so a '<'
    flip is drift, not noise."""
    if not fmt:
        return []
    endian = ""
    chars = fmt
    if fmt[0] in "<>!=@":
        endian = {"<": "le", ">": "be", "!": "be"}.get(fmt[0], "")
        chars = fmt[1:]
    out = []
    for ch in chars:
        base = _STRUCT_TOKENS.get(ch)
        if base is None:
            raise ExtractError(f"unhandled struct format char {ch!r} in {fmt!r}")
        out.append(base + endian if base not in ("u8",) else base)
    return out


# ---- the token-unit symbolic evaluator -------------------------------------


class _Emitter:
    """Walks a function body in Python evaluation order, emitting wire
    field tokens. `helpers` maps expandable helper names to their defs
    (cross-module: cluster/codec.py + ops/ujson_wire.py)."""

    def __init__(self, helpers: dict[str, ast.AST]):
        self.helpers = helpers
        self._stack: list[str] = []

    # -- entry points

    def sequence(self, fn: ast.AST) -> list:
        out: list = []
        for stmt in fn.body:
            out.extend(self.stmt(stmt))
        return out

    def expand(self, name: str) -> list:
        if name in self._stack:
            raise ExtractError(f"recursive helper expansion: {name}")
        fn = self.helpers.get(name)
        if fn is None:
            raise ExtractError(f"unknown codec helper: {name}")
        self._stack.append(name)
        try:
            return self.sequence(fn)
        finally:
            self._stack.pop()

    # -- statements

    def stmt(self, node: ast.AST) -> list:
        if isinstance(node, ast.Expr):
            return self.expr(node.value)
        if isinstance(node, ast.Assign):
            return self.expr(node.value) + sum(
                (self.expr(t) for t in node.targets), []
            )
        if isinstance(node, ast.AugAssign):
            return self.expr(node.value)
        if isinstance(node, ast.AnnAssign):
            return self.expr(node.value) if node.value is not None else []
        if isinstance(node, ast.Return):
            return self.expr(node.value) if node.value is not None else []
        if isinstance(node, ast.For):
            body = []
            for s in node.body:
                body.extend(self.stmt(s))
            # a loop over a LITERAL tuple/list runs a known number of
            # times: unroll (the p2set writer iterates (adds, removes))
            if isinstance(node.iter, (ast.Tuple, ast.List)):
                return self.expr(node.iter) + body * len(node.iter.elts)
            head = self.expr(node.iter)
            return head + ([["rep", body]] if body else [])
        if isinstance(node, ast.If):
            test = self.expr(node.test)
            then = []
            for s in node.body:
                then.extend(self.stmt(s))
            other = []
            for s in node.orelse:
                other.extend(self.stmt(s))
            if then or other:
                raise ExtractError(
                    f"conditional field at line {node.lineno}: branch-"
                    "dependent wire shapes need a dispatch unit, not an "
                    "inline if"
                )
            return test
        if isinstance(node, ast.While):
            raise ExtractError(f"while-loop in codec body at line {node.lineno}")
        if isinstance(node, ast.Raise):
            return []
        if isinstance(node, (ast.Pass, ast.Break, ast.Continue, ast.Global)):
            return []
        if isinstance(node, ast.Try):
            out = []
            for s in node.body:
                out.extend(self.stmt(s))
            return out
        if isinstance(node, ast.With):
            out = []
            for s in node.body:
                out.extend(self.stmt(s))
            return out
        raise ExtractError(
            f"unhandled statement {type(node).__name__} at line {node.lineno}"
        )

    # -- expressions (evaluation order)

    def expr(self, node: ast.AST) -> list:
        if node is None or isinstance(node, (ast.Constant, ast.Name)):
            return []
        if isinstance(node, ast.Call):
            return self.call(node)
        if isinstance(node, ast.Attribute):
            return self.expr(node.value)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return sum((self.expr(e) for e in node.elts), [])
        if isinstance(node, ast.Dict):
            out = []
            for k, v in zip(node.keys, node.values):
                out.extend(self.expr(k) if k is not None else [])
                out.extend(self.expr(v))
            return out
        if isinstance(node, ast.BinOp):
            return self.expr(node.left) + self.expr(node.right)
        if isinstance(node, ast.BoolOp):
            return sum((self.expr(v) for v in node.values), [])
        if isinstance(node, ast.UnaryOp):
            return self.expr(node.operand)
        if isinstance(node, ast.Compare):
            return self.expr(node.left) + sum(
                (self.expr(c) for c in node.comparators), []
            )
        if isinstance(node, ast.Subscript):
            return self.expr(node.value) + self.expr(node.slice)
        if isinstance(node, ast.Slice):
            out = []
            for part in (node.lower, node.upper, node.step):
                if part is not None:
                    out.extend(self.expr(part))
            return out
        if isinstance(node, (ast.GeneratorExp, ast.ListComp, ast.SetComp)):
            head, body = self._comp_parts(node.generators)
            body.extend(self.expr(node.elt))
            return head + ([["rep", body]] if body else [])
        if isinstance(node, ast.DictComp):
            head, body = self._comp_parts(node.generators)
            body.extend(self.expr(node.key))
            body.extend(self.expr(node.value))
            return head + ([["rep", body]] if body else [])
        if isinstance(node, ast.IfExp):
            return self.expr(node.test) + self.expr(node.body) + self.expr(
                node.orelse
            )
        if isinstance(node, ast.Starred):
            return self.expr(node.value)
        if isinstance(node, ast.JoinedStr):
            return []
        raise ExtractError(
            f"unhandled expression {type(node).__name__} at line "
            f"{getattr(node, 'lineno', '?')}"
        )

    def _comp_parts(self, generators) -> tuple[list, list]:
        if len(generators) != 1:
            raise ExtractError("nested comprehension in codec body")
        gen = generators[0]
        head = self.expr(gen.iter)
        body: list = []
        for cond in gen.ifs:
            body.extend(self.expr(cond))
        return head, body

    def call(self, node: ast.Call) -> list:
        name = _dotted(node.func)
        tail = name.split(".")[-1]
        # writer primitives
        if tail == "_w_varint":
            return self._args_tokens(node, skip=2) + ["varint"]
        if tail == "_w_bytes":
            return self._args_tokens(node, skip=2) + ["bytes"]
        if tail == "_w_str":
            return self._args_tokens(node, skip=2) + ["str"]
        if name.endswith("out.append"):
            arg = node.args[0] if node.args else None
            if isinstance(arg, ast.Name):
                return [f"u8:{arg.id}"]
            return ["u8"]
        if name.endswith("out.extend"):
            return self._args_tokens(node) + ["raw"]
        # reader primitives (receiver named r)
        if name == "r.varint":
            return ["varint"]
        if name == "r.bytes_":
            return ["bytes"]
        if name == "r.str_":
            return ["str"]
        # struct widths
        if tail in ("pack", "unpack", "unpack_from") and name.startswith("struct."):
            fmt = node.args[0]
            if not (isinstance(fmt, ast.Constant) and isinstance(fmt.value, str)):
                raise ExtractError(f"non-literal struct format at {node.lineno}")
            return _struct_tokens(fmt.value) + sum(
                (self.expr(a) for a in node.args[1:]), []
            )
        # the per-type delta dispatchers are their own units: emit one
        # abstract token here so msg/PushDeltas stays comparable
        if tail in ("_w_delta", "_r_delta"):
            return ["delta"]
        # expandable helpers (cluster codec writers/readers + read_ujson)
        if tail.startswith(("_w_", "_r_")) or tail == "read_ujson":
            args = self._args_tokens(node)
            return args + self.expand(tail)
        # anything else: a value-level call — walk args for nested reads
        return self._args_tokens(node)

    def _args_tokens(self, node: ast.Call, skip: int = 0) -> list:
        out = []
        for a in node.args[skip:]:
            out.extend(self.expr(a))
        for kw in node.keywords:
            out.extend(self.expr(kw.value))
        return out


def _branch_key_encode(test: ast.AST) -> list[str]:
    """isinstance(msg, MsgPong) -> ['Pong']."""
    if (
        isinstance(test, ast.Call)
        and _dotted(test.func) == "isinstance"
        and len(test.args) == 2
    ):
        cname = _dotted(test.args[1])
        if cname.startswith("Msg"):
            return [cname[3:]]
    return []


def _branch_key_tag(test: ast.AST) -> list[str]:
    """tag == _TAG_PONG -> ['Pong']."""
    if isinstance(test, ast.Compare) and len(test.comparators) == 1:
        for side in (test.left, test.comparators[0]):
            name = _dotted(side)
            if name in TAG_UNITS:
                return [name]
    return []


def _branch_key_name(test: ast.AST) -> list[str]:
    """name == "TREG" / name in ("TLOG", "SYSTEM") -> the type keys."""
    if isinstance(test, ast.Compare) and len(test.comparators) == 1:
        comp = test.comparators[0]
        keys = []
        cands = comp.elts if isinstance(comp, (ast.Tuple, ast.List, ast.Set)) else [comp]
        for c in cands:
            if isinstance(c, ast.Constant) and isinstance(c.value, str):
                keys.append(c.value)
        return [k for k in keys if k in DELTA_TYPES]
    return []


def _dispatch_branches(fn: ast.AST, keyer) -> dict[str, list[ast.stmt]]:
    """Split a dispatcher function into {branch key: body statements}
    from its top-level if/elif chain (both the statement-chain and the
    early-return styles)."""
    out: dict[str, list[ast.stmt]] = {}

    def eat(node: ast.If):
        keys = keyer(node.test)
        for k in keys:
            out[k] = node.body
        for e in node.orelse:
            if isinstance(e, ast.If):
                eat(e)

    for stmt in fn.body:
        if isinstance(stmt, ast.If):
            eat(stmt)
    return out


def extract_message_units(
    codec_tree: ast.Module | None = None, wire_tree: ast.Module | None = None
) -> dict[str, dict[str, list]]:
    """{unit: {encode: seq, decode: seq}} for the six cluster messages
    and the per-type delta payloads."""
    codec_tree = codec_tree if codec_tree is not None else _parse(CODEC_REL)
    wire_tree = wire_tree if wire_tree is not None else _parse(UJSON_WIRE_REL)
    fns = _functions(codec_tree)
    helpers = dict(fns)
    helpers.update(_functions(wire_tree))
    em = _Emitter(helpers)

    units: dict[str, dict[str, list]] = {}
    enc = _dispatch_branches(fns["_encode_oracle"], _branch_key_encode)
    dec = _dispatch_branches(fns["_decode_oracle"], _branch_key_tag)
    # remap decode branch keys (_TAG_X) to unit names, prefixing the tag
    # byte the shared `tag = body[0]` read consumed
    dec_by_unit = {}
    for tag_const, unit in TAG_UNITS.items():
        body = dec.get(tag_const)
        if body is None:
            raise ExtractError(f"no decode branch for {tag_const}")
        seq = []
        for s in body:
            seq.extend(em.stmt(s))
        dec_by_unit[unit] = [f"u8:{tag_const}"] + seq
    for unit in TAG_UNITS.values():
        if unit not in enc:
            raise ExtractError(f"no encode branch for Msg{unit}")
        seq = []
        for s in enc[unit]:
            seq.extend(em.stmt(s))
        units[f"msg/{unit}"] = {
            "encode": seq, "decode": dec_by_unit[unit]
        }

    enc_d = _dispatch_branches(fns["_w_delta"], _branch_key_name)
    dec_d = _dispatch_branches(fns["_r_delta"], _branch_key_name)
    for t in DELTA_TYPES:
        if t not in enc_d or t not in dec_d:
            raise ExtractError(f"no delta branch for {t}")
        e, d = [], []
        for s in enc_d[t]:
            e.extend(em.stmt(s))
        for s in dec_d[t]:
            d.extend(em.stmt(s))
        units[f"delta/{t}"] = {"encode": e, "decode": d}
    return units


# ---- atom units ------------------------------------------------------------

# canonical atom vocabulary: dotted-name tail -> atom
_ATOM_CALLS = {
    "delta_signature": "delta_signature",
    "legacy_snapshot_signatures": "legacy_accepted",
    "legacy_delta_signatures": "legacy_accepted",
    "frame": "framing",
    "FrameReader": "framing",
    "build_header": "framing",
    "parse_header": "framing",
    "encode": "body",
    "decode": "body",
    "crc32": "crc",
}
_ATOM_NAMES = {"MAGIC": "MAGIC", "HEADER_LEN": "", "header": ""}


def _atoms(fn: ast.AST) -> list[str]:
    """First-touch-ordered canonical atoms in one function (pre-order:
    a call's atom lands before its arguments')."""
    seen: list[str] = []

    def touch(a: str):
        if a and a not in seen:
            seen.append(a)

    def walk(node: ast.AST):
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            tail = name.split(".")[-1]
            if tail in ("pack", "unpack", "unpack_from") and name.startswith(
                "struct."
            ):
                fmt = node.args[0]
                if isinstance(fmt, ast.Constant) and isinstance(fmt.value, str):
                    for tok in _struct_tokens(fmt.value):
                        touch(tok)
            elif tail in _ATOM_CALLS:
                touch(_ATOM_CALLS[tail])
        elif isinstance(node, ast.Name) and node.id in _ATOM_NAMES:
            touch(_ATOM_NAMES[node.id])
        for child in ast.iter_child_nodes(node):
            walk(child)

    walk(fn)
    return seen


def _class_method(tree: ast.Module, cls: str, method: str) -> ast.AST:
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == cls:
            for m in node.body:
                if (
                    isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and m.name == method
                ):
                    return m
    raise ExtractError(f"{cls}.{method} not found")


def extract_atom_units(root: str = ROOT) -> dict[str, dict]:
    framing = _functions(_parse(FRAMING_REL, root))
    cluster = _functions(_parse(CLUSTER_REL, root))
    journal_tree = _parse(JOURNAL_REL, root)
    journal = _functions(journal_tree)
    persist = _functions(_parse(PERSIST_REL, root))

    units: dict[str, dict] = {}
    units["frame/header"] = {
        "grade": "atoms",
        "encode": _atoms(framing["build_header"]),
        "decode": _atoms(framing["parse_header"]),
    }
    # the writer-side framing header is added by frame() here; the
    # reader side's FrameReader lives in the cluster read loop, one
    # function out — ignore the framing atom rather than invent an edge
    units["frame/wire"] = {
        "grade": "atoms",
        "ignore": ["framing"],
        "encode": _atoms(cluster["wire_frame"]),
        "decode": _atoms(cluster["check_frame"]),
    }
    # journal: header written by _open_fresh_file, frames by _run;
    # read_journal consumes both
    writer = _atoms(_class_method(journal_tree, "Journal", "_open_fresh_file"))
    for a in _atoms(_class_method(journal_tree, "Journal", "_run")):
        if a not in writer:
            writer.append(a)
    jreader = _atoms(journal["read_journal"])
    units["file/journal"] = {
        "grade": "atoms",
        "encode": writer,
        # legacy-signature acceptance is a version flag, not a wire
        # field (the file/snapshot precedent)
        "decode": [a for a in jreader if a != "legacy_accepted"],
        "accepts_legacy": "legacy_accepted" in jreader,
    }
    loader = _atoms(persist["load_snapshot"])
    units["file/snapshot"] = {
        "grade": "atoms",
        "encode": _atoms(persist["write_snapshot"]),
        "decode": [a for a in loader if a != "legacy_accepted"],
        "accepts_legacy": "legacy_accepted" in loader,
    }
    return units


# ---- schema identity + native pins -----------------------------------------


def _module_const(tree: ast.Module, name: str):
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == name
        ):
            return node.value
    return None


def _eval_schema_text(tree: ast.Module) -> tuple[int, str]:
    """SCHEMA_VERSION plus the rendered _SCHEMA_TEXT (its only
    interpolation is SCHEMA_VERSION itself)."""
    vnode = _module_const(tree, "SCHEMA_VERSION")
    if not (isinstance(vnode, ast.Constant) and isinstance(vnode.value, int)):
        raise ExtractError("SCHEMA_VERSION not a literal int")
    version = vnode.value
    tnode = _module_const(tree, "_SCHEMA_TEXT")
    if isinstance(tnode, ast.Constant) and isinstance(tnode.value, str):
        return version, tnode.value
    if isinstance(tnode, ast.JoinedStr):
        parts = []
        for v in tnode.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            elif (
                isinstance(v, ast.FormattedValue)
                and isinstance(v.value, ast.Name)
                and v.value.id == "SCHEMA_VERSION"
            ):
                parts.append(str(version))
            else:
                raise ExtractError("_SCHEMA_TEXT interpolates more than the version")
        return version, "".join(parts)
    raise ExtractError("_SCHEMA_TEXT not found")


def _legacy_versions(tree: ast.Module) -> list[int]:
    out = []
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id.startswith("_LEGACY_V")
            and node.targets[0].id.endswith("_TEXT")
        ):
            try:
                out.append(int(node.targets[0].id[len("_LEGACY_V"):-len("_TEXT")]))
            except ValueError:
                pass
    return sorted(out)


def extract_native_pins(root: str = ROOT) -> dict[str, dict]:
    """Per-type FFI argument layout of native/codec.py: the order of the
    flattened field buffers each _encode_*/_decode_* hands to C++."""
    tree = _parse(NATIVE_CODEC_REL, root)
    pins: dict[str, dict] = {}
    for node in tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        if not node.name.startswith(("_encode_", "_decode_")):
            continue
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            name = _dotted(call.func)
            if not name.startswith("cdll.jy_"):
                continue
            args = [ast.unparse(a) for a in call.args]
            pins[node.name] = {"ffi": name.split(".", 1)[1], "args": args}
            break
    return pins


# ---- manifest --------------------------------------------------------------


def build_manifest(root: str = ROOT) -> dict:
    codec_tree = _parse(CODEC_REL, root)
    version, schema_text = _eval_schema_text(codec_tree)
    units = extract_message_units(codec_tree, _parse(UJSON_WIRE_REL, root))
    units.update(extract_atom_units(root))
    return {
        "_comment": (
            "Generated by `python -m scripts.jlint --write-manifest` from "
            "the paired encoder/decoder sources (cluster/codec.py + "
            "framing.py + cluster.py wire frame, journal/journal.py, "
            "persist.py, native/codec.py FFI layout, ops/ujson_wire.py) — "
            "do not edit by hand. `make lint` fails on encoder/decoder "
            "field-sequence asymmetry (JL701/JL702) and on any drift "
            "between this file and the extracted truth (JL703). The "
            "golden corpus (tests/golden/codec_corpus.json) pins this "
            "file's sha256; regenerate it with --write-corpus after any "
            "manifest change."
        ),
        "schema_version": version,
        "schema_sha256": hashlib.sha256(schema_text.encode()).hexdigest(),
        "legacy_snapshot_versions": _legacy_versions(codec_tree),
        "units": {k: units[k] for k in sorted(units)},
        "native": extract_native_pins(root),
    }


def write_manifest(path: str = CODEC_MANIFEST_PATH) -> dict:
    manifest = build_manifest()
    with open(path, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
        f.write("\n")
    return manifest


def manifest_sha(path: str = CODEC_MANIFEST_PATH) -> str:
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _flat(seq: list) -> list[str]:
    out = []
    for item in seq:
        if isinstance(item, list) and item and item[0] == "rep":
            out.append("rep[")
            out.extend(_flat(item[1]))
            out.append("]")
        else:
            out.append(str(item))
    return out


def unit_findings(units: dict[str, dict]) -> list[Finding]:
    """JL701/JL702 symmetry findings over extracted units (split out so
    the classification is pinnable on fixtures)."""
    out: list[Finding] = []
    for unit, entry in units.items():
        enc, dec = _flat(entry["encode"]), _flat(entry["decode"])
        if entry.get("grade") == "atoms":
            # multi-function writer/reader pairs: first-touch ORDER is a
            # construction artifact (a payload is encoded before it is
            # framed; a reader parses the frame first) — the invariant is
            # that both sides touch exactly the same atoms
            ignore = set(entry.get("ignore", ()))
            missing = (set(enc) - set(dec)) - ignore
            extra = (set(dec) - set(enc)) - ignore
            for atom, side in ((missing, "reader"), (extra, "writer")):
                if atom:
                    out.append(
                        Finding(
                            "JL702", CODEC_REL, 1,
                            f"`{unit}`: the {side} never touches "
                            f"{sorted(atom)} — a written field no reader "
                            "consumes (or a reader expecting bytes the "
                            "writer never produces)",
                            unit,
                        )
                    )
            continue
        if enc == dec:
            continue
        n = min(len(enc), len(dec))
        if enc[:n] == dec[:n]:
            longer, shorter = ("encoder", "decoder") if len(enc) > len(dec) else (
                "decoder", "encoder"
            )
            extra = (enc if len(enc) > len(dec) else dec)[n:]
            out.append(
                Finding(
                    "JL702", CODEC_REL, 1,
                    f"`{unit}`: the {longer} handles trailing field(s) "
                    f"{extra} the {shorter} never touches — an encoded "
                    "field no decoder consumes (or a decoder reading "
                    "past the wire shape)",
                    unit,
                )
            )
        else:
            i = next(
                (k for k in range(n) if enc[k] != dec[k]), n
            )
            out.append(
                Finding(
                    "JL701", CODEC_REL, 1,
                    f"`{unit}`: encoder/decoder field sequences diverge at "
                    f"position {i}: encode={enc[max(0, i - 2): i + 3]} vs "
                    f"decode={dec[max(0, i - 2): i + 3]} — order/width/"
                    "endianness drift",
                    unit,
                )
            )
    return out


def check(
    manifest_path: str = CODEC_MANIFEST_PATH, root: str = ROOT
) -> list[Finding]:
    out: list[Finding] = []
    rel = os.path.relpath(manifest_path, ROOT)
    try:
        current = build_manifest(root)
    except ExtractError as e:
        out.append(
            Finding(
                "JL701", CODEC_REL, 1,
                f"codec extraction failed — the encoder/decoder idiom "
                f"drifted outside what pass 7 can prove symmetric: {e}",
                "",
            )
        )
        return out
    out += unit_findings(current["units"])

    if not os.path.exists(manifest_path):
        out.append(
            Finding(
                "JL703", rel, 1,
                "codec manifest missing — run `python -m scripts.jlint "
                "--write-manifest` and commit it",
                "",
            )
        )
        return out
    with open(manifest_path, encoding="utf-8") as f:
        committed = json.load(f)
    for key in (
        "schema_version", "schema_sha256", "legacy_snapshot_versions",
        "units", "native",
    ):
        if committed.get(key) != current[key]:
            out.append(
                Finding(
                    "JL703", rel, 1,
                    f"codec manifest drift in `{key}` — the committed "
                    "manifest no longer matches the extracted "
                    "encoder/decoder truth; run `python -m scripts.jlint "
                    "--write-manifest`, review the diff, commit (and "
                    "re-record the golden corpus with --write-corpus)",
                    key,
                )
            )
    return out


# ---- golden corpus ---------------------------------------------------------

CORPUS_PATH = os.path.join(ROOT, "tests", "golden", "codec_corpus.json")


def build_corpus() -> dict:
    """Deterministic golden bytes for every unit and every live schema
    version. Imports the product (jax-free modules only at import time
    for the codec path) — corpus generation and the tier-1 test pay
    that, `make lint` never does."""
    import sys

    if ROOT not in sys.path:
        sys.path.insert(0, ROOT)
    from jylis_tpu.cluster import codec
    from jylis_tpu.cluster.framing import frame
    from jylis_tpu.cluster.msg import (
        MsgAnnounceAddrs,
        MsgDeltaAck,
        MsgDigestTree,
        MsgExchangeAddrs,
        MsgIntervalReset,
        MsgPong,
        MsgPushDeltas,
        MsgRangeRequest,
        MsgRegionGossip,
        MsgRelayPush,
        MsgSeqPush,
        MsgSyncDone,
        MsgSyncRequest,
    )
    from jylis_tpu.ops.compose import pack_field
    from jylis_tpu.ops.p2set import P2Set
    from jylis_tpu.ops.tensor_host import Tensor
    from jylis_tpu.ops.ujson_host import UJSON
    from jylis_tpu.utils.address import Address
    import struct
    import zlib

    def tensor_deltas():
        """One key per merge mode, so all three TENSOR shapes byte-pin."""
        lww = Tensor.lww(struct.pack("<2f", 1.5, -2.0), ts=9, rid=3)
        av = Tensor.avg(1, 4, struct.pack("<2f", 0.5, 0.25))
        av.converge(Tensor.avg(2, 6, struct.pack("<2f", 8.0, 1.0)))
        return (
            (b"kmax", Tensor.max_value(struct.pack("<2f", 1.0, -0.0))),
            (b"klww", lww),
            (b"kavg", av),
        )

    def ujson_delta() -> UJSON:
        u = UJSON()
        u.entries[(1, 1)] = (("a", "b"), '"x"')
        u.entries[(2, 5)] = (("a",), "42")
        u.ctx.vv = {1: 1, 2: 5}
        u.ctx.cloud = {(3, 9)}
        return u

    def _span_exemplar(one_hop: bool) -> bytes:
        """A real jtrace chain: origin at a varint-edge timestamp, plus
        (for the relay unit) a relay stamp — pins the hop framing."""
        from jylis_tpu.obs import jtrace

        span = jtrace.append_hop(
            b"", jtrace.HOP_ORIGIN, "h1:6001:n1!7", "eu-west", 128
        )
        if not one_hop:
            span = jtrace.append_hop(
                span, jtrace.HOP_RELAY, "h2:6002:n2!1", "eu-west",
                1700000000000,
            )
        return span

    p2 = P2Set()
    p2.adds = {Address("h1", "6001", "n1"), Address("h2", "6002", "n2")}
    p2.removes = {Address("h3", "6003", "n3")}

    messages = {
        "msg/Pong": MsgPong(),
        "msg/SyncDone": MsgSyncDone(
            (("h1:6001:n1!7", 300),)  # v10 digest-match svec, non-empty
        ),
        "msg/ExchangeAddrs": MsgExchangeAddrs(p2),
        "msg/AnnounceAddrs": MsgAnnounceAddrs(p2),
        # v10: the sync pair carries the session vector — pinned with
        # epoch-bearing rids and a varint-edge seq
        "msg/SyncRequest": MsgSyncRequest(
            (b"\x01" * 32, b"\x02" * 32),
            (("h1:6001:n1!7", 127), ("h2:6002:n2!1700000000000", 128)),
        ),
        # schema v8 units, byte-pinned: cum/seq at varint edge values
        # (127/128 straddle the LEB128 continuation bit), a sparse tree
        # with first+last buckets, a budget-shaped range request, and
        # the reset at a two-byte varint
        "msg/DeltaAck": MsgDeltaAck(127),
        "msg/SeqPush": MsgSeqPush(
            128, 127, "GCOUNT", ((b"k1", {1: 10, 2: 20}),)
        ),
        "msg/DigestTree": MsgDigestTree(
            "PNCOUNT", ((0, b"\x03" * 32), (255, b"\x04" * 32))
        ),
        "msg/RangeRequest": MsgRangeRequest("PNCOUNT", (0, 64, 255)),
        "msg/IntervalReset": MsgIntervalReset(300),
        # v10: the origin-preserving relay (seq at a varint edge, the
        # origin rid with its epoch suffix, batch = msg3's bytes) and
        # the region gossip map
        "msg/RelayPush": MsgRelayPush(
            128, "h1:6001:n1!7", 127, "GCOUNT", ((b"k1", {1: 10, 2: 20}),)
        ),
        # schema v11: the SAME sequenced/relay frames carrying a sampled
        # provenance span (transport-only field; the span bytes here are
        # a real two-hop jtrace chain with a varint-edge timestamp, so
        # the byte pin covers the hop framing too)
        "msg/SeqPushSpan": MsgSeqPush(
            128, 127, "GCOUNT", ((b"k1", {1: 10, 2: 20}),),
            _span_exemplar(one_hop=True),
        ),
        "msg/RelayPushSpan": MsgRelayPush(
            128, "h1:6001:n1!7", 127, "GCOUNT", ((b"k1", {1: 10, 2: 20}),),
            _span_exemplar(one_hop=False),
        ),
        "msg/RegionGossip": MsgRegionGossip(
            (("h1:6001:n1", "eu-west", 127),
             ("h2:6002:n2", "us-east", 1700000000000))
        ),
        "delta/TREG": MsgPushDeltas("TREG", ((b"k1", (b"v1", 7)),)),
        "delta/TLOG": MsgPushDeltas(
            "TLOG", ((b"k1", ([(b"e2", 9), (b"e1", 3)], 2)),)
        ),
        "delta/SYSTEM": MsgPushDeltas(
            "SYSTEM", ((b"_log", ([(b"boot", 11)], 0)),)
        ),
        "delta/GCOUNT": MsgPushDeltas("GCOUNT", ((b"k1", {1: 10, 2: 20}),)),
        "delta/PNCOUNT": MsgPushDeltas(
            "PNCOUNT", ((b"k1", ({1: 10}, {2: 4})),)
        ),
        "delta/UJSON": MsgPushDeltas("UJSON", ((b"k1", ujson_delta()),)),
        "delta/TENSOR": MsgPushDeltas("TENSOR", tensor_deltas()),
        # v9 recursive MAP units: one key per registered inner lattice
        # (content + tombstone evidence), plus a tombstone-only unit
        # whose val is the inner bottom — every branch of the recursive
        # shape byte-pins
        "delta/MAP": MsgPushDeltas(
            "MAP",
            (
                (pack_field(b"m1", b"ftreg"),
                 ("TREG", {1: 2, 2: 1}, {1: 1}, (b"v", 7))),
                (pack_field(b"m1", b"ftlog"),
                 ("TLOG", {3: 1}, {}, (((b"e1", 9), (b"e0", 3)), 2))),
                (pack_field(b"m2", b"fg"),
                 ("GCOUNT", {1: 1}, {}, {1: 10, 2: 20})),
                (pack_field(b"m2", b"fpn"),
                 ("PNCOUNT", {2: 3}, {}, ({1: 10}, {2: 4}))),
                (pack_field(b"m2", b"dead"),
                 ("GCOUNT", {1: 1}, {1: 1}, {})),
            ),
        ),
        # v9 escrow counter: the five-component full view with both
        # transfer matrices populated (varint edges exercised by the
        # 127/128 amounts)
        "delta/BCOUNT": MsgPushDeltas(
            "BCOUNT",
            ((b"inv", ({1: 128}, {1: 127, 2: 4}, {2: 3},
                       {(1, 2): 16}, {(2, 1): 5, (1, 3): 1})),),
        ),
    }
    entries: dict[str, dict] = {}
    for name, msg in sorted(messages.items()):
        body = codec._encode_oracle(msg)
        entries[name] = {"hex": body.hex()}

    # frame/wire: CRC+origin transport frame at a FIXED origin stamp
    from jylis_tpu.cluster.cluster import wire_frame

    body = codec._encode_oracle(MsgPong())
    entries["frame/wire"] = {
        "hex": wire_frame(body, origin_ms=1234567890123).hex(),
        "origin_ms": 1234567890123,
    }
    # file/journal: header + two CRC frames (one per type family)
    payload1 = codec._encode_oracle(messages["delta/GCOUNT"])
    payload2 = codec._encode_oracle(messages["delta/TREG"])
    journal_blob = b"JYLJRNL1" + codec.delta_signature()
    for p in (payload1, payload2):
        journal_blob += frame(struct.pack(">I", zlib.crc32(p)) + p)
    entries["file/journal"] = {"hex": journal_blob.hex()}
    # file/snapshot: header + one frame per data type (wire-delta dump)
    snap_blob = b"JYLSNAP1" + codec.delta_signature()
    for name in (
        "TREG", "TLOG", "GCOUNT", "PNCOUNT", "UJSON", "TENSOR", "MAP",
        "BCOUNT", "SYSTEM",
    ):
        key = "delta/" + name
        snap_blob += frame(codec._encode_oracle(messages[key]))
    entries["file/snapshot"] = {"hex": snap_blob.hex()}

    return {
        "_comment": (
            "Golden codec corpus, generated by `python -m scripts.jlint "
            "--write-corpus` — do not edit by hand. "
            "tests/test_codec_corpus.py round-trips every entry through "
            "the oracle codec and (where present) the native fast path, "
            "and pins manifest_sha256 against "
            "scripts/jlint/codec_manifest.json: a schema/manifest edit "
            "without a corpus re-record fails in tier-1."
        ),
        "manifest_sha256": manifest_sha(),
        "delta_signature": codec.delta_signature().hex(),
        "legacy_snapshot_signatures": [
            s.hex() for s in codec.legacy_snapshot_signatures()
        ],
        "entries": entries,
    }


def write_corpus(path: str = CORPUS_PATH) -> dict:
    corpus = build_corpus()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(corpus, f, indent=2, sort_keys=True)
        f.write("\n")
    return corpus
