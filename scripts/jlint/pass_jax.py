"""Pass 2 — JAX trace discipline over ``jylis_tpu/ops/`` (rules JL2xx).

The merge kernels' speed rests on trace discipline: a host sync inside a
jit function serialises the device pipeline, a Python branch on a traced
value either crashes at trace time or silently bakes one side into the
compiled program, an implicit dtype leaves promotion to the ambient
``jax_enable_x64`` state (the lattices are u64; the documented guard is
``with enable_x64(False)`` around kernel-dtype blocks — see bench.py's
Pallas tensor-merge kernel), and a ``jax.jit`` constructed per call throws
the compile cache away every time.

Reachability: a function is "jit code" when decorated with ``jax.jit`` /
``@partial(jax.jit, …)`` (static args read from ``static_argnums`` /
``static_argnames``), or when a jit-decorated function in the same
module calls it by name (transitively).
"""

from __future__ import annotations

import ast

from . import Finding, Source, dotted_name, parent_map

HOST_SYNC_METHODS = {"item", "block_until_ready", "tolist"}
HOST_SYNC_CALLS = {"np.asarray", "np.array", "jax.device_get", "numpy.asarray", "numpy.array"}
HOST_CASTS = {"float", "int", "bool"}
DTYPE_IMPLICIT_CTORS = {
    "jnp.asarray", "jnp.array", "jnp.zeros", "jnp.ones", "jnp.full",
    "jnp.empty", "jnp.arange",
}
SHAPE_ATTRS = {"shape", "ndim", "dtype", "size"}
# function-name prefixes allowed to construct jits (setup, not serving)
JIT_CTOR_OK_PREFIXES = ("__init__", "make", "build", "_make", "_build", "warm", "setup")


def _jit_decorator_info(fn: ast.FunctionDef):
    """(is_jit, static_param_names) from the decorator list."""
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted_name(target)
        inner = None
        if name.endswith("partial") and isinstance(dec, ast.Call) and dec.args:
            inner = dotted_name(dec.args[0])
            if not (inner == "jit" or inner.endswith(".jit")):
                continue
        elif not (name == "jit" or name.endswith(".jit")):
            continue
        static: set[str] = set()
        if isinstance(dec, ast.Call):
            params = [a.arg for a in fn.args.args]
            for kw in dec.keywords:
                if kw.arg == "static_argnames" and isinstance(
                    kw.value, (ast.Tuple, ast.List, ast.Constant)
                ):
                    elts = (
                        kw.value.elts
                        if isinstance(kw.value, (ast.Tuple, ast.List))
                        else [kw.value]
                    )
                    static |= {
                        e.value for e in elts
                        if isinstance(e, ast.Constant) and isinstance(e.value, str)
                    }
                if kw.arg == "static_argnums" and isinstance(
                    kw.value, (ast.Tuple, ast.List, ast.Constant)
                ):
                    elts = (
                        kw.value.elts
                        if isinstance(kw.value, (ast.Tuple, ast.List))
                        else [kw.value]
                    )
                    for e in elts:
                        if isinstance(e, ast.Constant) and isinstance(e.value, int):
                            if 0 <= e.value < len(params):
                                static.add(params[e.value])
        return True, static
    return False, set()


def _module_functions(tree: ast.AST) -> dict[str, ast.FunctionDef]:
    return {
        n.name: n
        for n in ast.walk(tree)
        if isinstance(n, ast.FunctionDef)
    }


def _jit_reachable(tree: ast.AST):
    """{fn_name: static_params} for jit roots and their same-module
    callees (callees inherit an empty static set — conservatively every
    parameter of a helper is treated as traced)."""
    fns = _module_functions(tree)
    reach: dict[str, set[str]] = {}
    frontier: list[str] = []
    for name, fn in fns.items():
        is_jit, static = _jit_decorator_info(fn)
        if is_jit:
            reach[name] = static
            frontier.append(name)
    while frontier:
        cur = frontier.pop()
        for node in ast.walk(fns[cur]):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                callee = node.func.id
                if callee in fns and callee not in reach:
                    reach[callee] = set()
                    frontier.append(callee)
    return fns, reach


def _in_x64_guard(node: ast.AST, parents) -> bool:
    while node in parents:
        node = parents[node]
        if isinstance(node, ast.With):
            for item in node.items:
                if "enable_x64" in ast.unparse(item.context_expr):
                    return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
    return False


def _walk_body(fn: ast.FunctionDef):
    """Own body statements only — decorators are not the body (a
    `@partial(jax.jit, …)` decorator is the sanctioned spelling, not a
    per-call jit), and nested defs get their own reachability entry."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _traced_param_names(fn: ast.FunctionDef, static: set[str]) -> set[str]:
    args = fn.args
    names = {
        a.arg
        for a in args.args + args.posonlyargs + args.kwonlyargs
        if a.arg not in ("self", "cls")
    }
    return names - static


def _shape_derived(expr: ast.AST, static_locals: set[str]) -> bool:
    """Does the expression bottom out in trace-time shape data —
    `.shape`/`.ndim`/`len(…)` anywhere inside, or a local previously
    assigned from one?"""
    for n in ast.walk(expr):
        if isinstance(n, ast.Attribute) and n.attr in SHAPE_ATTRS:
            return True
        if (
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Name)
            and n.func.id == "len"
        ):
            return True
        if isinstance(n, ast.Name) and n.id in static_locals:
            return True
    return False


def _static_locals(fn: ast.FunctionDef) -> set[str]:
    """Locals assigned from shape-derived expressions (transitively):
    `w = plane.shape[-1]` makes `w` a trace-time constant."""
    static: set[str] = set()
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if (
                    isinstance(t, ast.Name)
                    and t.id not in static
                    and _shape_derived(node.value, static)
                ):
                    static.add(t.id)
                    changed = True
    return static


def _name_use_is_static_shaped(
    name_node: ast.Name, parents, static_locals: set[str]
) -> bool:
    """Uses that read trace-time constants, not traced data:
    `param.shape[0] > 1` (any attribute chain reaching .shape/.ndim/
    .dtype), `len(param)`, `isinstance(param, …)`, `param is None`, and
    comparisons whose other side is shape-derived (`if width == w` where
    `w = plane.shape[-1]` — the host-static width convention)."""
    node: ast.AST = name_node
    while True:
        parent = parents.get(node)
        if isinstance(parent, ast.Attribute):
            if parent.attr in SHAPE_ATTRS:
                return True
            node = parent
            continue
        if isinstance(parent, ast.Subscript) and node is parent.value:
            node = parent
            continue
        break
    if isinstance(parent, ast.Call) and isinstance(parent.func, ast.Name):
        if parent.func.id in ("len", "isinstance"):
            return True
    if isinstance(parent, ast.Compare):
        if any(isinstance(op, (ast.Is, ast.IsNot)) for op in parent.ops):
            return True
        others = [
            o
            for o in [parent.left] + list(parent.comparators)
            if o is not node
        ]
        if others and all(_shape_derived(o, static_locals) for o in others):
            return True
    return False


def run(sources: list[Source]) -> list[Finding]:
    out: list[Finding] = []
    for src in sources:
        parents = parent_map(src.tree)
        fns, reach = _jit_reachable(src.tree)

        # JL204 applies module-wide (jit construction anywhere hot)
        for fn in ast.walk(src.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name.startswith(JIT_CTOR_OK_PREFIXES):
                continue
            for node in _walk_body(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                is_jit_ctor = name == "jit" or name.endswith(".jit")
                if not is_jit_ctor and name.endswith("partial") and node.args:
                    inner = dotted_name(node.args[0])
                    is_jit_ctor = inner == "jit" or inner.endswith(".jit")
                if is_jit_ctor:
                    out.append(
                        Finding(
                            "JL204", src.rel, node.lineno,
                            f"`jax.jit` constructed inside `{fn.name}` — a "
                            "fresh jit per call discards the compile cache; "
                            "hoist it to module level or a setup path",
                            src.line_src(node.lineno),
                        )
                    )

        for name, static in sorted(reach.items()):
            fn = fns[name]
            traced = _traced_param_names(fn, static)
            statics = _static_locals(fn)
            for node in _walk_body(fn):
                # JL201: host syncs
                if isinstance(node, ast.Call):
                    cname = dotted_name(node.func)
                    if cname in HOST_SYNC_CALLS:
                        out.append(
                            Finding(
                                "JL201", src.rel, node.lineno,
                                f"`{cname}` inside jit-reachable `{name}` — "
                                "forces a device->host sync under trace",
                                src.line_src(node.lineno),
                            )
                        )
                    elif (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr in HOST_SYNC_METHODS
                        and not node.args
                    ):
                        out.append(
                            Finding(
                                "JL201", src.rel, node.lineno,
                                f"`.{node.func.attr}()` inside jit-reachable "
                                f"`{name}` — host sync on a traced value",
                                src.line_src(node.lineno),
                            )
                        )
                    elif (
                        isinstance(node.func, ast.Name)
                        and node.func.id in HOST_CASTS
                        and node.args
                        and isinstance(node.args[0], ast.Name)
                        and node.args[0].id in traced
                    ):
                        out.append(
                            Finding(
                                "JL201", src.rel, node.lineno,
                                f"`{node.func.id}({node.args[0].id})` inside "
                                f"jit-reachable `{name}` — concretises a "
                                "traced value on the host",
                                src.line_src(node.lineno),
                            )
                        )
                    # JL203: dtype-implicit constructors
                    if (
                        dotted_name(node.func) in DTYPE_IMPLICIT_CTORS
                        and not any(kw.arg == "dtype" for kw in node.keywords)
                        and len(node.args) < 2  # positional dtype (2nd arg)
                        and not _in_x64_guard(node, parents)
                    ):
                        out.append(
                            Finding(
                                "JL203", src.rel, node.lineno,
                                f"`{dotted_name(node.func)}` without an "
                                f"explicit dtype inside jit-reachable "
                                f"`{name}` — result dtype depends on the "
                                "ambient x64 state; pass dtype= or guard "
                                "with enable_x64",
                                src.line_src(node.lineno),
                            )
                        )
                # JL202: data-dependent branching
                if isinstance(node, (ast.If, ast.While)):
                    for n in ast.walk(node.test):
                        if (
                            isinstance(n, ast.Name)
                            and n.id in traced
                            and not _name_use_is_static_shaped(
                                n, parents, statics
                            )
                        ):
                            out.append(
                                Finding(
                                    "JL202", src.rel, node.lineno,
                                    f"Python branch on traced `{n.id}` inside "
                                    f"jit-reachable `{name}` — use lax.cond/"
                                    "jnp.where, or mark the arg static",
                                    src.line_src(node.lineno),
                                )
                            )
                            break
    return out
