"""Open-loop multi-process load harness (the overload drill's engine).

Closed-loop load generators measure a server's ability to make ITS
clients wait: when the node slows down, a closed loop offers less load,
and the latency distribution silently heals. Overload behavior only
shows under an OPEN loop — commands are sent on a fixed schedule
whether or not earlier replies have arrived, and each command's latency
is measured from its SCHEDULED send time, so queueing delay (the thing
overload actually inflicts on users) is part of the number.

Shape:

* N worker PROCESSES (``--procs``), each one connection, each split
  into a sender thread (schedules sends at the offered rate, pipelines
  onto the socket) and a receiver thread (parses replies FIFO, matches
  them to scheduled times, buckets per class). Workers are spawned as
  ``--worker`` re-executions of this script with a JSON config argv —
  no multiprocessing pickling, no jax import (the whole script is
  stdlib + sockets, so a worker boots in milliseconds).
* Zipfian key skew (``--zipf-s``, YCSB's 0.99 default) plus REGIONAL
  skew: ``--region-frac`` of ops target ``<region>:``-prefixed keys,
  modeling the home-region bias a geo-placed workload has.
* Sustained-overload phases: ``--mults 1,2,4`` runs the same mix at
  1x, 2x, 4x of the base rate (``--base-rate``, or calibrated to
  ``CALIB_FRAC`` of measured closed-loop capacity when 0), recording
  per-phase per-class sent/ok/busy/err, shed fractions, read/write
  latency percentiles (p50/p99/p99.9), and the delta of the node's
  OVERLOAD counters (SYSTEM METRICS) across the phase.

The command mix is deliberately two-class: reads are plain ``GCOUNT
GET`` (the protected class under the default admission policy) and
writes are ``SESSION WRAP GCOUNT INC`` — session-wrapped exactly so the
classifier's WRAP-unwrapping is load-bearing in every drill that uses
this harness (a first-word classifier would never shed them).

``--smoke`` boots a throwaway node (forced-shed failpoint armed so the
BUSY path is exercised deterministically), runs a two-phase micro-run,
and asserts the output shape — the ``make ci`` loadgen-smoke step.
bench.py's ``overload-shed`` config drives this script as a subprocess
and asserts the acceptance bound on the recorded numbers.
"""

from __future__ import annotations

import argparse
import bisect
import json
import math
import os
import random
import socket
import subprocess
import sys
import threading
import time
from collections import deque

# base rate = this fraction of the last sustainable probe rung. The
# headroom is deliberate, on both ends of the phase ladder: the 1x
# phase must be CLEANLY under capacity on a noisy shared host (the
# drill's contract compares 4x tails against it, and the probe
# criterion sits near the admission enter threshold), and the 4x phase
# must stay inside the REFUSAL path's own throughput ceiling — a shed
# command still costs a parse, a classify and a typed reply, so at
# 0.85 x rung the 4x write flood outran even the refusal path on a
# small host and the protected tail drowned in arrival backlog.
CALIB_FRAC = 0.50
LAT_CAP = 50_000  # reservoir size per class per worker

READ = "read"
WRITE = "write"


# ---- a tiny standalone RESP client (no jylis_tpu import) -------------------


def _pack(args: list[bytes]) -> bytes:
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        out.append(b"$%d\r\n%s\r\n" % (len(a), a))
    return b"".join(out)


class _Conn:
    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.settimeout(timeout)
        self.buf = b""

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def _fill(self) -> None:
        chunk = self.sock.recv(1 << 16)
        if not chunk:
            raise RuntimeError("connection closed by server")
        self.buf += chunk

    def _line(self) -> bytes:
        while b"\r\n" not in self.buf:
            self._fill()
        line, self.buf = self.buf.split(b"\r\n", 1)
        return line

    def read_reply(self):
        """One reply; error replies return ("err", text) instead of
        raising so the receiver can bucket them without try/except."""
        line = self._line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest
        if kind == b"-":
            return ("err", rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n < 0:
                return None
            while len(self.buf) < n + 2:
                self._fill()
            out, self.buf = self.buf[:n], self.buf[n + 2 :]
            return out
        if kind == b"*":
            n = int(rest)
            if n < 0:
                return None
            return [self.read_reply() for _ in range(n)]
        raise RuntimeError(f"unparseable reply line: {line!r}")

    def call(self, *words: bytes):
        self.sock.sendall(_pack(list(words)))
        return self.read_reply()


# ---- key skew ---------------------------------------------------------------


class Zipf:
    """Rank sampler over ``n`` keys with exponent ``s`` via inverse-CDF
    bisect (n is small enough that the precomputed CDF is cheap)."""

    def __init__(self, n: int, s: float):
        weights = [1.0 / math.pow(r, s) for r in range(1, n + 1)]
        total = sum(weights)
        acc, cdf = 0.0, []
        for w in weights:
            acc += w / total
            cdf.append(acc)
        self.cdf = cdf

    def rank(self, rng: random.Random) -> int:
        return bisect.bisect_left(self.cdf, rng.random())


# ---- the worker (one process, one connection, open loop) -------------------


def _reservoir(samples: list[float], count: int, v: float,
               rng: random.Random) -> None:
    if len(samples) < LAT_CAP:
        samples.append(v)
    else:
        j = rng.randrange(count)
        if j < LAT_CAP:
            samples[j] = v


def run_worker(cfg: dict) -> dict:
    rng = random.Random(cfg["seed"])
    zipf = Zipf(cfg["keys"], cfg["zipf_s"])
    region = cfg["region"].encode()
    conn = _Conn(cfg["host"], cfg["port"], timeout=120.0)
    sent = {READ: 0, WRITE: 0}
    ok = {READ: 0, WRITE: 0}
    busy = {READ: 0, WRITE: 0}
    err = {READ: 0, WRITE: 0}
    lat = {READ: [], WRITE: []}
    nlat = {READ: 0, WRITE: 0}
    pending: deque = deque()
    done_sending = threading.Event()
    fail: list[str] = []

    # warmup exclusion, standard open-loop practice (wrk2's --latency
    # discards its calibration window the same way): the first seconds
    # of an overload phase are the hysteresis streak plus the standing
    # backlog it admits before declaring — by design, not steady state.
    # Counters (sent/ok/busy) still cover the whole phase; only the
    # latency reservoir starts after warmup.
    warm_until = [0.0]

    def recv() -> None:
        try:
            while True:
                if not pending:
                    if done_sending.is_set():
                        return
                    time.sleep(0.001)
                    continue
                reply = conn.read_reply()
                cls, t_sched = pending.popleft()
                dt = time.monotonic() - t_sched
                is_err = isinstance(reply, tuple) and reply[0] == "err"
                if is_err and reply[1].startswith("BUSY"):
                    busy[cls] += 1
                elif is_err:
                    err[cls] += 1
                else:
                    ok[cls] += 1
                    if t_sched >= warm_until[0]:
                        nlat[cls] += 1
                        _reservoir(lat[cls], nlat[cls], dt * 1e3, rng)
        except (OSError, RuntimeError) as e:
            fail.append(f"receiver: {e}")

    rx = threading.Thread(target=recv, daemon=True)
    rx.start()

    interval = 1.0 / cfg["rate"]
    t0 = time.monotonic()
    warm_until[0] = t0 + cfg.get("warmup_s", 0.0)
    end = t0 + cfg["duration_s"]
    i = 0
    try:
        while True:
            t_sched = t0 + i * interval
            if t_sched >= end or fail:
                break
            now = time.monotonic()
            if t_sched > now:
                time.sleep(min(t_sched - now, 0.005))
                continue
            if rng.random() < cfg["region_frac"] and region:
                key = b"%s:k%d" % (region, zipf.rank(rng))
            else:
                key = b"k%d" % zipf.rank(rng)
            if rng.random() < cfg["read_frac"]:
                cls, payload = READ, _pack([b"GCOUNT", b"GET", key])
            else:
                cls, payload = WRITE, _pack(
                    [b"SESSION", b"WRAP", b"GCOUNT", b"INC", key, b"1"]
                )
            # enqueue BEFORE the (possibly blocking) send: the reply
            # can arrive while sendall is parked on TCP backpressure
            pending.append((cls, t_sched))
            sent[cls] += 1
            conn.sock.sendall(payload)
            i += 1
    except OSError as e:
        fail.append(f"sender: {e}")
    done_sending.set()
    rx.join(timeout=cfg["duration_s"] + 60.0)
    conn.close()
    return {
        "sent": sent, "ok": ok, "busy": busy, "err": err,
        "lat_ms": lat, "failures": fail,
    }


# ---- parent: calibration, phases, metrics deltas ---------------------------


def _metrics_overload(host: str, port: int) -> dict[str, int]:
    c = _Conn(host, port, timeout=30.0)
    try:
        lines = c.call(b"SYSTEM", b"METRICS")
    finally:
        c.close()
    out: dict[str, int] = {}
    for raw in lines if isinstance(lines, list) else []:
        if isinstance(raw, bytes) and raw.startswith(b"OVERLOAD "):
            _, key, val = raw.decode().split(" ", 2)
            out[key] = int(val)
    return out


def calibrate(host: str, port: int, procs: int, seconds: float,
              read_frac: float) -> float:
    """OPEN-loop capacity probe at the workload mix: a rate ladder
    (x1.5 per rung, ``seconds`` per rung) of short in-process open-loop
    runs, stopping at the first rung where the p99 from SCHEDULED send
    time breaches ``_PROBE_P99_MS`` or the node refuses/errs — i.e. the
    first rung the node cannot actually sustain. Returns the last
    sustained rate.

    A closed-loop probe is the obvious alternative and is WRONG here:
    batched request/reply pipelining keeps the whole stream on the
    native serving path, measuring a ceiling 2-3x above what the same
    mix sustains open-loop (where backlog routes commands through the
    per-command Python path). Calibrating against it declares overload
    at 1x and the drill's baseline phase is meaningless."""
    del seconds  # rung length is fixed; kept for CLI compat
    rate = 400.0 * procs
    good = rate / 1.5
    for _ in range(14):
        results = _inline_open_loop(
            host, port, procs, rate, _PROBE_S, read_frac
        )
        lat = sorted(
            v for r in results for cls in (READ, WRITE)
            for v in r["lat_ms"][cls]
        )
        bad = (
            any(r["failures"] for r in results)
            or sum(r["busy"][c] for r in results for c in (READ, WRITE)) > 0
            or sum(r["err"][c] for r in results for c in (READ, WRITE)) > 0
            or not lat
            or _pctl(lat, 0.99) > _PROBE_P99_MS
        )
        if bad:
            break
        good = rate
        rate *= 1.5
        time.sleep(0.3)  # let the probe's tail drain before the next rung
    return good


_PROBE_S = 2.0
_PROBE_P99_MS = 15.0


def _inline_open_loop(host, port, procs, total_rate, duration_s,
                      read_frac):
    """``procs`` open-loop workers as in-process threads (calibration
    only — the measured phases use real worker processes)."""
    results: list[dict] = [None] * procs  # type: ignore[list-item]

    def drive(idx: int) -> None:
        results[idx] = run_worker({
            "host": host, "port": port, "rate": total_rate / procs,
            "duration_s": duration_s, "seed": 1000 + idx,
            "keys": 64, "zipf_s": 0.99, "read_frac": read_frac,
            "region": "", "region_frac": 0.0,
        })

    threads = [
        threading.Thread(target=drive, args=(i,)) for i in range(procs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return [r for r in results if r is not None]


def _pctl(sorted_ms: list[float], q: float) -> float:
    if not sorted_ms:
        return 0.0
    idx = min(len(sorted_ms) - 1, int(q * len(sorted_ms)))
    return sorted_ms[idx]


def _log2_hist(sorted_ms: list[float]) -> list[list[float]]:
    """``[[upper_ms, count], ...]`` — the full latency distribution as
    log2 buckets (sample counted under the smallest power-of-two upper
    bound >= its value), empty buckets dropped. Three percentiles hide
    bimodality — an admitted-vs-queued split under overload shows as two
    humps here — and the bucket shape diffs cleanly across CI runs."""
    counts: dict[float, int] = {}
    for v in sorted_ms:
        m, e = math.frexp(max(v, 1e-3))  # clamp: sub-µs is one bucket
        if m == 0.5:  # exact power of two belongs in its own bucket
            e -= 1
        upper = math.ldexp(1.0, e)
        counts[upper] = counts.get(upper, 0) + 1
    return [[u, counts[u]] for u in sorted(counts)]


def _merge_phase(results: list[dict], mult: float, offered: float,
                 od: dict, phase_s: float) -> dict:
    agg: dict = {
        "mult": mult, "offered_rate": round(offered, 1),
        "sent": {READ: 0, WRITE: 0}, "ok": {READ: 0, WRITE: 0},
        "busy": {READ: 0, WRITE: 0}, "err": {READ: 0, WRITE: 0},
        "failures": [],
    }
    lat = {READ: [], WRITE: []}
    for r in results:
        for k in ("sent", "ok", "busy", "err"):
            for cls in (READ, WRITE):
                agg[k][cls] += r[k][cls]
        for cls in (READ, WRITE):
            lat[cls].extend(r["lat_ms"][cls])
        agg["failures"].extend(r["failures"])
    agg["shed_frac"] = {
        cls: round(agg["busy"][cls] / max(agg["sent"][cls], 1), 4)
        for cls in (READ, WRITE)
    }
    agg["throughput"] = {
        cls: round(agg["ok"][cls] / max(phase_s, 1e-9), 1)
        for cls in (READ, WRITE)
    }
    agg["lat_ms"] = {}
    for cls in (READ, WRITE):
        s = sorted(lat[cls])
        agg["lat_ms"][cls] = {
            "n": len(s),
            "p50": round(_pctl(s, 0.50), 3),
            "p99": round(_pctl(s, 0.99), 3),
            "p999": round(_pctl(s, 0.999), 3),
            "hist_log2_ms": _log2_hist(s),
        }
    agg["overload_delta"] = od
    return agg


def run_phases(args) -> dict:
    base = args.base_rate
    if base <= 0:
        cap = calibrate(args.host, args.port, args.procs, args.calib_s,
                        args.read_frac)
        base = max(cap * CALIB_FRAC, float(args.procs))
    mults = [float(m) for m in args.mults.split(",")]
    phases = []
    for mult in mults:
        offered = base * mult
        # quiesce: don't let the previous phase's declared overload /
        # standing backlog bleed into this phase's baseline. Exiting
        # takes EXIT_STREAK consecutive calm samples and these polls
        # are the only traffic feeding the state machine — poll fast
        # so the streak can complete inside the window (the first poll
        # after the idle gap resets the stale EWMA, admission.py).
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if _metrics_overload(args.host, args.port).get("state", 0) == 0:
                break
            time.sleep(0.05)
        # then go quiet past the idle-reset window: the phase's first
        # admitted command starts the EWMA fresh (admission.py
        # IDLE_RESET_S) instead of averaging against quiesce residue
        time.sleep(1.5)
        before = _metrics_overload(args.host, args.port)
        procs = []
        for w in range(args.procs):
            cfg = {
                "host": args.host, "port": args.port,
                "rate": offered / args.procs,
                "duration_s": args.phase_s,
                "warmup_s": args.warmup_s,
                "seed": args.seed + w + int(mult * 1000),
                "keys": args.keys, "zipf_s": args.zipf_s,
                "read_frac": args.read_frac,
                "region": args.region, "region_frac": args.region_frac,
            }
            procs.append(
                subprocess.Popen(
                    [sys.executable, os.path.abspath(__file__),
                     "--worker", json.dumps(cfg)],
                    stdout=subprocess.PIPE,
                )
            )
        results = []
        for p in procs:
            stdout, _ = p.communicate(timeout=args.phase_s + 120)
            if p.returncode != 0:
                raise RuntimeError(f"worker failed rc={p.returncode}")
            results.append(json.loads(stdout))
        after = _metrics_overload(args.host, args.port)
        od = {
            k: after.get(k, 0) - before.get(k, 0)
            for k in sorted(set(before) | set(after))
            if k not in ("state", "ewma_us", "inflight", "queued_bytes")
        }
        od["state_after"] = after.get("state", 0)
        phases.append(
            _merge_phase(results, mult, offered, od, args.phase_s)
        )
    return {
        "base_rate": round(base, 1),
        "procs": args.procs,
        "phase_s": args.phase_s,
        "read_frac": args.read_frac,
        "zipf_s": args.zipf_s,
        "phases": phases,
    }


# ---- smoke (make ci) --------------------------------------------------------

_SMOKE_SPAWN = (
    "import jax; jax.config.update('jax_platforms','cpu'); "
    "import sys; from jylis_tpu.main import main; main(sys.argv[1:])"
)


def smoke() -> dict:
    """Boot a throwaway node with the forced-shed failpoint on a hit
    budget, run a micro two-phase open loop, and assert the recorded
    shape: served ops in both phases, BUSY refusals recorded as shed
    (not errors), and latency percentiles present."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    node = subprocess.Popen(
        [sys.executable, "-c", _SMOKE_SPAWN, "--port", str(port),
         "--addr", "127.0.0.1:9999:lg", "--log-level", "warn",
         "--admission-policy", "control>read>write>bulk",
         "--failpoints", "admission.shed=error:40"],
        cwd=repo, env=env,
    )
    try:
        deadline = time.time() + 120
        while True:
            try:
                _Conn("127.0.0.1", port, timeout=5.0).close()
                break
            except OSError:
                if node.poll() is not None or time.time() > deadline:
                    raise RuntimeError("smoke node never came up")
                time.sleep(0.3)
        args = argparse.Namespace(
            host="127.0.0.1", port=port, procs=2, phase_s=1.0,
            mults="1,4", base_rate=300.0, calib_s=0.0, keys=32,
            zipf_s=0.99, read_frac=0.7, region="", region_frac=0.0,
            seed=7, warmup_s=0.0,
        )
        out = run_phases(args)
    finally:
        node.terminate()
        node.wait(timeout=60)
    assert len(out["phases"]) == 2, out
    total_ok = sum(
        p["ok"][c] for p in out["phases"] for c in (READ, WRITE)
    )
    total_busy = sum(
        p["busy"][c] for p in out["phases"] for c in (READ, WRITE)
    )
    assert total_ok > 100, f"barely served: {out}"
    assert total_busy > 0, f"forced-shed failpoint never refused: {out}"
    assert all(
        p["err"][c] == 0 for p in out["phases"] for c in (READ, WRITE)
    ), f"BUSY must bucket as shed, not error: {out}"
    assert out["phases"][0]["lat_ms"][READ]["p99"] > 0.0
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=6379)
    ap.add_argument("--procs", type=int, default=4)
    ap.add_argument("--phase-s", type=float, default=10.0)
    ap.add_argument("--mults", default="1,2,4",
                    help="comma list of offered-load multipliers")
    ap.add_argument("--base-rate", type=float, default=0.0,
                    help="ops/s at 1x; 0 = calibrate to "
                         f"{CALIB_FRAC:.0%} of the open-loop probe "
                         "ladder's last sustainable rung")
    ap.add_argument("--calib-s", type=float, default=2.0)
    ap.add_argument("--warmup-s", type=float, default=1.0,
                    help="per-phase seconds excluded from the latency "
                         "reservoir (the hysteresis entry transient); "
                         "counters still cover the whole phase")
    ap.add_argument("--keys", type=int, default=512)
    ap.add_argument("--zipf-s", type=float, default=0.99)
    ap.add_argument("--read-frac", type=float, default=0.7)
    ap.add_argument("--region", default="",
                    help="home region for the regional key skew")
    ap.add_argument("--region-frac", type=float, default=0.0,
                    help="fraction of ops on <region>:-prefixed keys")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--out", default="",
                    help="also write the per-phase JSON artifact here "
                         "(sorted keys, trailing newline — diffable "
                         "across CI runs like lint_findings.json)")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--worker", default="",
                    help=argparse.SUPPRESS)  # internal re-exec
    args = ap.parse_args(argv)
    if args.worker:
        json.dump(run_worker(json.loads(args.worker)), sys.stdout)
        return 0
    out = smoke() if args.smoke else run_phases(args)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
            f.write("\n")
    print(json.dumps(out, indent=1))
    if args.smoke:
        print("loadgen smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
