"""Prose-vs-record guard (round-5 verdict item 4).

Every headline number in README.md must be derived from the committed
BENCH_full.json — this script regenerates the expected prose token for
each pinned claim from the record and fails if the README does not
contain it. Re-recording the sweep without updating the prose (or vice
versa) fails CI instead of shipping a disagreement.

Run via `make check-prose` (part of `make ci`).
"""

from __future__ import annotations

import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def fmt_millions(v: float) -> str:
    return f"{v / 1e6:.1f}M"


def fmt_ratio(v: float) -> str:
    return f"{v:.1f}×"


def fmt_percent(v: float) -> str:
    return f"{v * 100:.0f}%"


def fmt_frac(v: float) -> str:
    return f"{v:.4f}"


def fmt_us(v: float) -> str:
    return f"{v:.0f} µs"


def fmt_ms(v: float) -> str:
    return f"{v / 1e3:.1f} ms"


def fmt_ms_plain(v: float) -> str:
    """A value already in ms (fmt_ms divides from µs)."""
    return f"{v:.1f} ms"


def fmt_thousands(v: float) -> str:
    return f"{v / 1e3:.0f}k"


# (file, config, record field, formatter, anchor template, human label):
# the formatted token substitutes into the template, and THAT phrase must
# appear verbatim in its file. Templates anchor each claim to its own
# sentence so a cross-sweep RANGE elsewhere in the prose (e.g.
# "0.9-1.2×") can never satisfy a drifted headline by substring accident.
CLAIMS = [
    ("README.md", "north-star", "value", fmt_millions,
     "**{}", "north-star merges/sec"),
    ("README.md", "north-star", "vs_baseline", fmt_ratio,
     "{} a vectorised-numpy", "north-star ratio"),
    ("README.md", "treg-1m", "vs_baseline", fmt_ratio,
     "TREG {}", "TREG ratio"),
    ("README.md", "tlog-trim", "vs_baseline", fmt_ratio,
     "TLOG {}", "TLOG ratio"),
    ("README.md", "ujson-multikey", "vs_baseline", fmt_ratio,
     "records **{}**", "UJSON deep-fan-in ratio"),
    ("README.md", "ujson-32", "vs_baseline", fmt_ratio,
     "edit stream {}", "UJSON 32-replica ratio"),
    ("README.md", "gcount-smoke", "value", fmt_millions,
     "**{} commands/sec**", "gcount-smoke commands/sec"),
    ("README.md", "gcount-smoke", "vs_baseline", fmt_ratio,
     "recorded, {} the bare", "gcount-smoke ratio"),
    ("README.md", "gcount-smoke", "engine_only", fmt_millions,
     "`engine_only` = {}", "gcount-smoke engine-only rate"),
    ("README.md", "gcount-smoke", "socket_cost_frac", fmt_percent,
     "`socket_cost_frac` = {}", "gcount-smoke socket cost"),
    ("README.md", "concurrent", "value", fmt_thousands,
     "**{} commands/sec**", "concurrent commands/sec"),
    ("README.md", "concurrent", "vs_baseline", fmt_ratio,
     "connections, {} the bare", "concurrent ratio"),
    ("README.md", "concurrent", "fallback_frac", fmt_frac,
     "`fallback_frac` = {}", "concurrent fallback fraction"),
    ("README.md", "serving-demotion", "vs_baseline", fmt_ratio,
     "demotion cliff of **{}**", "demotion cliff ratio"),
    ("README.md", "serving-latency", "p99_us_treg_get_64", fmt_ms,
     "p99 {} at 64", "latency p99 TREG GET 64 conns"),
    ("docs/operations.md", "serving-demotion", "vs_baseline", fmt_ratio,
     "measured cliff of {}", "operations doc demotion cliff"),
    ("docs/operations.md", "serving-latency", "p99_us_treg_get_64", fmt_ms,
     "costs {} at p99", "operations doc latency p99 (64 conns)"),
    ("docs/operations.md", "serving-latency", "p99_us_treg_get_1", fmt_us,
     "vs {} at one connection", "operations doc latency p99 (1 conn)"),
    ("docs/types/ujson.md", "serving-demotion", "vs_baseline", fmt_ratio,
     "demotion cliff of {} in", "ujson doc demotion cliff"),
    # type docs that cite BENCH_full.json by name carry the same duty
    ("docs/types/pncount.md", "north-star", "value", fmt_millions,
     "{} key-merges/sec recorded", "pncount doc merges/sec"),
    ("docs/types/ujson.md", "ujson-multikey", "vs_baseline", fmt_ratio,
     "stream: {} recorded", "ujson doc deep-fan-in ratio"),
    # round-5 verdict item 5: the number-carrying prose OUTSIDE the
    # original guard. Numbers in these files either derive from
    # BENCH_full.json (pinned here) or are explicitly marked in-text as
    # historical/round-stamped (e.g. PLAN.md's round-3 virtual-mesh
    # timings, ops/ujson_resident.py's round-3 environment numbers).
    ("jylis_tpu/parallel/PLAN.md", "north-star", "value", fmt_millions,
     "{} merges/s/chip recorded", "PLAN north-star merges/s"),
    # TENSOR round: the recorded tensor-merge numbers and the Pallas
    # settlement ratio, pinned wherever the prose claims them (the
    # pallas_join.py claims retired with the module)
    ("docs/tensor.md", "tensor-merge", "value", fmt_millions,
     "records **{} vector merges/sec**", "tensor doc merge rate"),
    ("docs/tensor.md", "tensor-merge", "vs_baseline", fmt_ratio,
     "{} the vectorised-numpy", "tensor doc merge ratio"),
    ("docs/tensor.md", "pallas-tensor-merge", "vs_baseline", fmt_frac,
     "recorded ratio of {}", "tensor doc pallas settlement ratio"),
    ("README.md", "tensor-merge", "value", fmt_millions,
     "TENSOR joins {} vector merges/sec", "README tensor merge rate"),
    ("docs/operations.md", "gcount-smoke", "socket_cost_frac", fmt_percent,
     "= {} of throughput", "operations doc socket cost"),
    ("docs/operations.md", "gcount-smoke", "engine_only", fmt_millions,
     "{} commands/sec vs", "operations doc engine-only rate"),
    ("docs/operations.md", "gcount-smoke", "value", fmt_millions,
     "vs {} served", "operations doc served rate"),
    ("docs/durability.md", "concurrent", "journal_cost_frac", fmt_percent,
     "journal costs {} of", "durability doc journal overhead"),
    # the failure-envelope section cites the demotion cliff for the
    # injected-FFI-fault path (robustness round)
    ("docs/operations.md", "serving-demotion", "vs_baseline", fmt_ratio,
     "at the recorded demotion cliff of {}", "failure envelope FFI cliff"),
    # observability round: the always-on histogram cost is a recorded
    # number (obs_cost_frac — the armed-vs-disarmed paired ratio on the
    # concurrent config), pinned wherever the prose claims the seams
    # are cheap enough to stay on
    ("README.md", "concurrent", "obs_cost_frac", fmt_percent,
     "histograms on cost {} of recorded", "README obs cost"),
    ("docs/observability.md", "concurrent", "obs_cost_frac", fmt_percent,
     "always-on seams cost {} of recorded", "observability doc obs cost"),
    # multi-lane round: the sharded record is the scaling artifact —
    # its headline, the lanes-vs-single-lane ratio (vs_baseline), and
    # the single-lane sweep's own 64-conn point, pinned wherever the
    # prose claims them (the recording host's core count bounds the
    # ratio; the record carries host_cores so the claim stays honest)
    ("README.md", "concurrent-sharded", "value", fmt_thousands,
     "**{} commands/sec** at 64 connections", "README sharded rate"),
    ("README.md", "concurrent-sharded", "vs_baseline", fmt_ratio,
     "ratio of {} on the 2-core recording host", "README sharded ratio"),
    ("docs/operations.md", "concurrent-sharded", "vs_baseline", fmt_ratio,
     "lanes-vs-single-lane ratio of {} at 64 connections",
     "operations doc sharded ratio"),
    # anti-entropy v2 round: the recorded rejoin-bytes ratio (range
    # repair vs whole-state dump at ~5% range-local divergence on the
    # 1M-key PNCOUNT store), pinned wherever the prose claims it
    ("README.md", "sync-divergence", "vs_baseline", fmt_ratio,
     "shipping {} fewer bytes", "README sync-divergence ratio"),
    ("docs/replication.md", "sync-divergence", "vs_baseline", fmt_ratio,
     "conversation at {} fewer bytes", "replication doc rejoin ratio"),
    ("docs/operations.md", "sync-divergence", "vs_baseline", fmt_ratio,
     "a rejoin at {} fewer bytes", "operations doc rejoin ratio"),
    ("docs/replication.md", "sync-divergence", "divergent_frac",
     lambda v: f"{v * 100:.2f}%",
     "divergent keys measured at {}", "replication doc divergence frac"),
    # composed-types round (schema v9): the MAP decomposition record
    # (hot-field-vs-whole-map ratio, the byte share against the 2%
    # acceptance bar, the field-scoped range pull) and the BCOUNT
    # contention record (end-to-end grants/sec, the local spend
    # ceiling, the refusal rate), pinned wherever the prose claims them
    ("docs/types/map.md", "map-hot-field", "value", fmt_ratio,
     "ships {} fewer bytes", "map doc hot-field ratio"),
    ("docs/types/map.md", "map-hot-field", "hot_field_pct",
     lambda v: f"{v:.4f}%", "just {} of a whole-map ship",
     "map doc hot-field byte share"),
    ("docs/types/map.md", "map-hot-field", "range_pulled_fields", str,
     "pulled only {} fields", "map doc range pull scope"),
    ("README.md", "map-hot-field", "value", fmt_ratio,
     "edit ships {} fewer bytes", "README map ratio"),
    ("docs/types/bcount.md", "bcount-contention", "value",
     lambda v: f"{v:.0f}", "sustains {} grants/sec end-to-end",
     "bcount doc contention grants"),
    ("docs/types/bcount.md", "bcount-contention", "local_grants_per_sec",
     fmt_millions, "admits {} grants/sec with escrow in hand",
     "bcount doc local spend ceiling"),
    ("docs/types/bcount.md", "bcount-contention", "refusal_rate",
     fmt_percent, "a {} refusal rate", "bcount doc refusal rate"),
    ("README.md", "bcount-contention", "local_grants_per_sec", fmt_millions,
     "escrow-checked spends at {} grants/sec", "README bcount rate"),
    # sessions & regions round (schema v10): the session path's tax on
    # plain serving latency (the <= 5% acceptance bar), and the
    # multi-region convergence lag against injected WAN RTT — pinned
    # in docs/sessions.md / operations.md and the README headline
    ("docs/sessions.md", "workload-zipf", "serving_latency_overhead_frac",
     lambda v: f"{v * 100:.2f}%", "measured at {} (bar: 5%)",
     "sessions doc serving-latency overhead"),
    ("README.md", "workload-zipf", "serving_latency_overhead_frac",
     lambda v: f"{v * 100:.2f}%", "a {} serving-latency tax",
     "README session overhead"),
    ("docs/operations.md", "wan-converge", "value",
     lambda v: f"{v:.1f} ms", "lag of {} at 80 ms injected RTT",
     "operations doc wan lag at 80ms"),
    ("docs/operations.md", "wan-converge", "base_lag_ms",
     lambda v: f"{v:.1f} ms", "a {} relay-path base", "operations doc wan base lag"),
    ("README.md", "wan-converge", "value",
     lambda v: f"{v:.1f} ms", "converges in {} under 80 ms", "README wan lag"),
    # bridge failover (PR 15): the recorded SIGKILL-to-reconverged gap
    # and the demotion window it is asserted against, pinned wherever
    # the prose claims the handover numbers
    ("docs/operations.md", "wan-converge", "failover_gap_80_ms",
     lambda v: f"{v:.1f} ms gap", "a {} at 80 ms injected RTT",
     "operations doc failover gap"),
    ("docs/operations.md", "wan-converge", "failover_demote_ticks",
     lambda v: f"{v:.0f}-tick", "the recorded {} × 0.2 s demotion window",
     "operations doc failover demotion window"),
    ("README.md", "wan-converge", "failover_gap_80_ms",
     lambda v: f"{v / 1e3:.1f} s",
     "measures a {} SIGKILL-to-reconverged gap", "README failover gap"),
    # overload-armor round: the sustained-overload drill's headline
    # numbers (the protected read tail at 4x offered load vs its 1x
    # value, the 4x write shed fraction) and the client-observed
    # failover MTTR, pinned wherever the prose claims them
    ("docs/operations.md", "overload-shed", "shed_frac_write_4x", fmt_frac,
     "sheds a {} write fraction", "operations doc 4x shed fraction"),
    ("docs/operations.md", "overload-shed", "value", fmt_ms_plain,
     "read p99.9 of {} at 4×", "operations doc 4x protected tail"),
    ("docs/operations.md", "overload-shed", "p999_1x_ms", fmt_ms_plain,
     "against {} at 1×", "operations doc 1x protected tail"),
    ("docs/client.md", "client-failover", "value",
     lambda v: f"{v * 1e3:.1f} ms",
     "read at {} worst-trial", "client doc failover MTTR"),
    ("README.md", "overload-shed", "shed_frac_write_4x", fmt_frac,
     "shedding a {} write fraction", "README 4x shed fraction"),
    ("README.md", "client-failover", "value",
     lambda v: f"{v * 1e3:.1f} ms",
     "fails over in {}", "README failover MTTR"),
]


# claims whose source of truth is a committed repo record OTHER than
# BENCH_full.json (the jlint v2 round: budget/manifest-derived numbers
# cited in docs/development.md must track the committed artifacts):
# (file, source json, extractor, formatter, anchor template, label)
REPO_CLAIMS = [
    ("docs/development.md", "scripts/jlint/budget.json",
     lambda d: d["recorded_seconds"], lambda v: f"~{v:.1f} s",
     "a full cold run records {}", "development doc lint recorded time"),
    ("docs/development.md", "scripts/jlint/budget.json",
     lambda d: d["budget_seconds"], lambda v: f"{v:.0f} s bound",
     "against a {}", "development doc lint budget bound"),
    ("docs/development.md", "scripts/jlint/lattice_manifest.json",
     lambda d: len(d["merge_roots"]), str,
     "({} merge roots)", "development doc merge-root count"),
    ("docs/development.md", "scripts/jlint/codec_manifest.json",
     lambda d: len(d["units"]), str,
     "({} units:", "development doc codec unit count"),
    # jmodel round: the smoke's recorded coverage + time and the
    # enforced floor are repo records (budget.json model_* entries) —
    # the prose must track them exactly like the lint budget
    ("docs/development.md", "scripts/jlint/budget.json",
     lambda d: d["model_recorded_states"], str,
     "explores {} distinct", "development doc jmodel recorded states"),
    ("docs/development.md", "scripts/jlint/budget.json",
     lambda d: d["model_recorded_seconds"], lambda v: f"~{v:.0f} s",
     "in {} on the recording host", "development doc jmodel recorded time"),
    ("docs/development.md", "scripts/jlint/budget.json",
     lambda d: d["model_min_states"], lambda v: f"{v / 1000:.0f}k-state floor",
     "below the {}", "development doc jmodel state floor"),
    # jlint v3 round: the native-surface burn-down number (ROADMAP
    # item 1) is the parity manifest's python_only count — surfaced in
    # lint_findings.json as counts.python_only and pinned here so the
    # prose tracks the record as commands move native; and the
    # semantics manifest's command count, which pass 11 requires to
    # cover the full native surface
    ("docs/development.md", "scripts/jlint/parity_manifest.json",
     lambda d: sum(len(v) for v in d["python_only"].values()), str,
     "declares {} commands still Python-only",
     "development doc python-only burn-down count"),
    ("docs/development.md", "scripts/jlint/semantics_manifest.json",
     lambda d: len(d["commands"]), str,
     "across all {} natively-served commands",
     "development doc semantics command count"),
]


# claims whose source of truth is a DEFAULT in the source tree (jtrace
# round: the observability doc quotes the --trace-sample and
# --converge-slo-ms defaults; changing Config without the prose — or
# vice versa — must fail here, not ship a lying doc):
# (file, source file, regex with one group, formatter, template, label)
SOURCE_CLAIMS = [
    ("docs/observability.md", "jylis_tpu/utils/config.py",
     r"trace_sample: int = (\d+)", str,
     "`--trace-sample N`, default {};", "observability doc trace-sample default"),
    ("docs/observability.md", "jylis_tpu/utils/config.py",
     r'converge_slo_ms: str = "([^"]+)"', str,
     "`--converge-slo-ms {}` (the default)",
     "observability doc converge-slo default"),
]


def main() -> int:
    with open(os.path.join(ROOT, "BENCH_full.json")) as f:
        record = {row["config"]: row for row in json.load(f)}
    texts = {}
    failures = []
    for fname, source, pattern, fmt, template, label in SOURCE_CLAIMS:
        if fname not in texts:
            with open(os.path.join(ROOT, fname)) as f:
                texts[fname] = f.read()
        with open(os.path.join(ROOT, source)) as f:
            m = re.search(pattern, f.read())
        if m is None:
            failures.append(
                f"  {label}: {source} no longer matches /{pattern}/"
            )
            continue
        expect = template.format(fmt(m.group(1)))
        if expect not in texts[fname]:
            failures.append(
                f"  {label}: {fname} lacks '{expect}' "
                f"({source} says {m.group(1)})"
            )
    for fname, source, extract, fmt, template, label in REPO_CLAIMS:
        if fname not in texts:
            with open(os.path.join(ROOT, fname)) as f:
                texts[fname] = f.read()
        with open(os.path.join(ROOT, source)) as f:
            value = extract(json.load(f))
        expect = template.format(fmt(value))
        if expect not in texts[fname]:
            failures.append(
                f"  {label}: {fname} lacks '{expect}' "
                f"({source} says {value})"
            )
    for fname, config, field, fmt, template, label in CLAIMS:
        if fname not in texts:
            with open(os.path.join(ROOT, fname)) as f:
                texts[fname] = f.read()
        value = record[config].get(field)
        if value is None:
            failures.append(
                f"  {label}: BENCH_full.json {config} lacks field "
                f"'{field}' (re-record with the native toolchain present?)"
            )
            continue
        expect = template.format(fmt(value))
        if expect not in texts[fname]:
            failures.append(
                f"  {label}: {fname} lacks '{expect}' "
                f"(BENCH_full.json {config}.{field} = {value})"
            )
    if failures:
        print("prose/record disagreement (update the prose or re-record):")
        print("\n".join(failures))
        return 1
    print(
        f"check-prose: {len(CLAIMS)} bench claims + {len(REPO_CLAIMS)} "
        f"repo-record claims + {len(SOURCE_CLAIMS)} source-default claims "
        f"across {len(texts)} files match their records"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
