"""CI metrics-smoke: boot a real node with --metrics-port, scrape it,
validate the Prometheus text exposition, and assert every histogram and
gauge declared in scripts/jlint/metrics_manifest.json is present from
boot (zero counts included — the observability surface must not depend
on traffic having happened).

Then boot a MULTI-LANE node (`--lanes N`, N from JYLIS_SMOKE_LANES,
default 4) and scrape the supervisor's AGGREGATED endpoint: every
manifest histogram must be present per lane (`lane="k"` labels for
every k), the counter families must also appear as aggregate
(lane-less) sums, every lane must report `jylis_lane_up 1`, and the
whole body must still be grammatically valid exposition — the per-lane
and aggregate metric surfaces can't rot independently.

Run via `make metrics-smoke` (part of `make ci`). Exit 0 = both
scrapes valid and complete, with non-trivial serving activity (the
script issues a few RESP commands first, so at least one seam has
samples).
"""

from __future__ import annotations

import http.client
import json
import os
import re
import socket
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MANIFEST = os.path.join(ROOT, "scripts", "jlint", "metrics_manifest.json")

# one exposition line: metric name, optional {labels}, a float value
SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\")*\})?"
    r" -?[0-9.eE+-]+( [0-9]+)?$"
)

SPAWN = (
    "import jax; jax.config.update('jax_platforms','cpu'); "
    "import sys; from jylis_tpu.main import main; main(sys.argv[1:])"
)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def scrape(port: int, timeout_s: float = 240.0) -> str:
    deadline = time.time() + timeout_s
    last: Exception | None = None
    while time.time() < deadline:
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            body = resp.read().decode()
            ctype = resp.getheader("Content-Type", "")
            conn.close()
            if resp.status != 200:
                raise RuntimeError(f"HTTP {resp.status}")
            if "text/plain" not in ctype:
                raise RuntimeError(f"bad content type: {ctype}")
            return body
        except (OSError, RuntimeError) as e:
            last = e
            time.sleep(1.0)
    raise RuntimeError(f"metrics endpoint never came up: {last!r}")


def resp_traffic(port: int, timeout_s: float = 180.0) -> None:
    """A few real commands so the dispatch seams have samples."""
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        try:
            s = socket.create_connection(("127.0.0.1", port), timeout=5)
            break
        except OSError:
            time.sleep(0.5)
    else:
        raise RuntimeError("RESP port never came up")
    s.sendall(
        b"GCOUNT INC smoke 3\r\nGCOUNT GET smoke\r\n"
        b"TLOG INS s x 1\r\nSYSTEM METRICS\r\n"
    )
    s.settimeout(30)
    got = b""
    while b"*" not in got:  # the METRICS array header arrived
        got += s.recv(1 << 16)
    s.close()


def _boot_and_scrape(lanes: int) -> str:
    resp_port = free_port()
    mport = free_port()
    args = [
        sys.executable, "-c", SPAWN,
        "--port", str(resp_port),
        "--addr", "127.0.0.1:0:metrics-smoke",
        "--metrics-port", str(mport),
        "--log-level", "warn",
    ]
    if lanes > 1:
        args += ["--lanes", str(lanes), "-T", "0.5"]
    proc = subprocess.Popen(args, cwd=ROOT, stdout=subprocess.DEVNULL)
    try:
        resp_traffic(resp_port)
        body = scrape(mport)
        # the aggregator answers as soon as IT is up, with whatever
        # lanes answer — re-scrape until every lane reports in (the
        # slowest lane can still be importing jax for a while on a
        # loaded CI host), then validate the complete surface
        deadline = time.time() + 240
        while lanes > 1 and time.time() < deadline and not all(
            f'jylis_lane_up{{lane="{k}"}} 1' in body for k in range(lanes)
        ):
            time.sleep(2.0)
            body = scrape(mport)
        return body
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


_HIST_FAMILY = "jylis_seam_latency_log2_seconds"
_HIST_LINE_RE = re.compile(
    rf"^{_HIST_FAMILY}_(bucket|count)\{{(?P<labels>[^}}]*)\}} (?P<v>\d+)$"
)
_LE_RE = re.compile(r'(?:^|,)le="([^"]+)"')


def _check_histograms(body: str, failures: list, tag: str,
                      hists: list[str]) -> int:
    """Validate the real-histogram exposition grammar: every manifest
    seam exposes a `_bucket` series whose counts are CUMULATIVE in le
    order, ends at le="+Inf", and whose `_count` equals the +Inf bucket
    — the invariants histogram_quantile() silently miscomputes without.
    Applies per series (so per-lane AND aggregated lane-less series on
    a lanes scrape are each checked). Returns the series count."""
    series: dict[str, list[tuple[float, int]]] = {}
    counts: dict[str, int] = {}
    for line in body.splitlines():
        m = _HIST_LINE_RE.match(line)
        if not m:
            continue
        labels, v = m.group("labels"), int(m.group("v"))
        if m.group(1) == "count":
            counts[labels] = v
            continue
        le = _LE_RE.search(labels)
        if le is None:
            failures.append(f"  [{tag}] _bucket without le: {line!r}")
            continue
        key = _LE_RE.sub("", labels)
        series.setdefault(key, []).append((float(le.group(1)), v))
    for key, pts in series.items():
        pts.sort()  # by le; float("+Inf") orders it last
        if pts[-1][0] != float("inf"):
            failures.append(f"  [{tag}] no le=\"+Inf\" bucket: {key}")
            continue
        vals = [v for _, v in pts]
        if any(b < a for a, b in zip(vals, vals[1:])):
            failures.append(
                f"  [{tag}] non-cumulative _bucket series: {key}"
            )
        if counts.get(key) != vals[-1]:
            failures.append(
                f"  [{tag}] _count != +Inf bucket for: {key}"
            )
    for name in hists:
        want = f'seam="{name}"'
        if not any(want in key for key in series):
            failures.append(
                f"  [{tag}] manifest seam has no _bucket series: {name}"
            )
    return len(series)


def _check_exposition(body: str, failures: list, tag: str) -> int:
    n_samples = 0
    for line in body.splitlines():
        if not line or line.startswith("#"):
            continue
        if not SAMPLE_RE.match(line):
            failures.append(f"  [{tag}] bad exposition line: {line!r}")
        else:
            n_samples += 1
    return n_samples


def main() -> int:
    manifest = json.load(open(MANIFEST))["metrics"]
    hists = sorted(n[5:] for n in manifest if n.startswith("hist:"))
    gauges = sorted(n[6:] for n in manifest if n.startswith("gauge:"))

    body = _boot_and_scrape(lanes=1)

    failures = []
    n_samples = _check_exposition(body, failures, "single")
    n_hist_series = _check_histograms(body, failures, "single", hists)
    for name in hists:
        if f'seam="{name}"' not in body:
            failures.append(f"  manifest histogram absent from scrape: {name}")
    for name in gauges:
        if f'name="{name}"' not in body:
            failures.append(f"  manifest gauge absent from scrape: {name}")
    # the traffic above must have armed the dispatch surface
    m = re.search(
        r'jylis_seam_latency_seconds_count\{seam="server\.(native_burst|'
        r'py_dispatch)"\} (\d+)',
        body,
    )
    counts = re.findall(
        r'jylis_seam_latency_seconds_count\{seam="server\.[a-z_]+"\} (\d+)',
        body,
    )
    if not m or not any(int(c) > 0 for c in counts):
        failures.append("  no dispatch-seam samples after RESP traffic")
    if "jylis_cmds_total" not in body:
        failures.append("  jylis_cmds_total family missing")

    # ---- the multi-lane aggregated scrape ----------------------------------
    lanes = int(os.environ.get("JYLIS_SMOKE_LANES", "4"))
    lane_body = _boot_and_scrape(lanes=lanes)
    n_lane_samples = _check_exposition(lane_body, failures, f"lanes={lanes}")
    n_lane_hist = _check_histograms(
        lane_body, failures, f"lanes={lanes}", hists
    )
    # the aggregator must ALSO sum buckets into lane-less series
    # (cumulative bucket counters sum correctly; quantiles never do)
    if not any(
        line.startswith(f"{_HIST_FAMILY}_bucket{{seam=")
        and 'lane="' not in line
        for line in lane_body.splitlines()
    ):
        failures.append(
            "  no aggregate (lane-less) _bucket series on the lanes scrape"
        )
    for k in range(lanes):
        if f'jylis_lane_up{{lane="{k}"}} 1' not in lane_body:
            failures.append(f"  lane {k} not up in the aggregated scrape")
        for name in hists:
            if f'lane="{k}",seam="{name}"' not in lane_body:
                failures.append(
                    f"  manifest histogram absent for lane {k}: {name}"
                )
        for name in gauges:
            if f'lane="{k}",name="{name}"' not in lane_body:
                failures.append(
                    f"  manifest gauge absent for lane {k}: {name}"
                )
    # counter families must ALSO exist as lane-less aggregate sums
    for family in ("jylis_cmds_total", "jylis_serving_total"):
        agg = [
            line for line in lane_body.splitlines()
            if line.startswith(family) and 'lane="' not in line
        ]
        if not agg:
            failures.append(f"  no aggregate (lane-less) {family} series")

    if failures:
        print("metrics-smoke FAILED:")
        print("\n".join(failures))
        return 1
    print(
        f"metrics-smoke: {n_samples} valid samples; {len(hists)} histograms"
        f" + {len(gauges)} gauges all present; {n_hist_series} cumulative "
        f"_bucket series valid; lanes={lanes} aggregate scrape: "
        f"{n_lane_samples} samples, {n_lane_hist} _bucket series, "
        f"per-lane + aggregate series ok"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
