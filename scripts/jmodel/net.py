"""The in-memory deterministic network + virtual clock under jmodel.

One ``ModelConn`` stands in for one TCP connection: two directed
``Link``s (dialer→target ``fwd``, target→dialer ``rev``), each a FIFO
of written-but-undelivered frames (``outbox``) plus a delivered-but-
unread byte buffer (``inbox``). ``Cluster`` writes through a
``ModelWriter`` exactly as it writes through an asyncio StreamWriter;
nothing moves from outbox to inbox until the explorer fires a
``deliver`` action — that withheld hop IS the schedule choice point.

Teardown is ABORTIVE, like a real socket teardown: ``kill()`` (what
``ModelWriter.close`` also routes to — a dropped conn, a partition, a
crash) discards everything in flight and EOFs both readers now. There
is deliberately no graceful close that keeps frames deliverable after
the connection dies: that wire exists on no TCP, and modelling it made
frames outlive their connection (a false in_flight counterexample).

The ``VirtualClock`` subclasses ``cluster.Clock``: ``now_ms`` advances
only on explorer ticks, ``perf`` is a strictly-increasing counter (rtt
stamps need ordering, not wall time). Both are deterministic, so the
same action trace always reproduces the same state — the property the
state-hash dedup, the sleep sets, and schedule replay all rest on.
"""

from __future__ import annotations

import asyncio

from jylis_tpu.cluster.cluster import Clock


class VirtualClock(Clock):
    __slots__ = ("ms", "_perf_n")

    def __init__(self, start_ms: int = 1_000_000):
        self.ms = start_ms
        self._perf_n = 0

    def now_ms(self) -> int:
        return self.ms

    def perf(self) -> float:
        self._perf_n += 1
        return self._perf_n * 1e-6

    def advance(self, ms: int) -> None:
        self.ms += ms


class Link:
    """One direction of a model connection."""

    __slots__ = ("key", "net", "outbox", "inbox", "closed", "_waiter")

    def __init__(self, key: str, net: "Network"):
        self.key = key
        self.net = net
        self.outbox: list[bytes] = []
        self.inbox = bytearray()
        self.closed = False
        self._waiter: asyncio.Future | None = None

    def _wake(self) -> None:
        w, self._waiter = self._waiter, None
        if w is not None and not w.done():
            w.set_result(None)

    def write(self, data: bytes) -> None:
        if not self.closed:
            self.outbox.append(bytes(data))
            self.net.progress += 1

    def deliver_one(self) -> None:
        """The explorer's `deliver` action: one written frame crosses."""
        if self.outbox:
            self.inbox.extend(self.outbox.pop(0))
            self.net.progress += 1
            self._wake()

    def duplicate_one(self) -> None:
        """The explorer's `dup` action: the head frame crosses as a COPY,
        the original stays queued — the receiver will see it twice
        (fire-and-forget + sync overlap makes redelivery a real
        schedule; the lattice join must absorb it)."""
        if self.outbox:
            self.inbox.extend(self.outbox[0])
            self.net.progress += 1
            self._wake()

    def kill(self) -> None:
        """Abortive: everything in flight is gone, EOF now."""
        self.closed = True
        self.outbox.clear()
        self.inbox.clear()
        self.net.progress += 1
        self._wake()

    @property
    def eof(self) -> bool:
        return self.closed and not self.outbox and not self.inbox


class ModelTransport:
    __slots__ = ("_conn",)

    def __init__(self, conn: "ModelConn"):
        self._conn = conn

    def is_closing(self) -> bool:
        return self._conn.closed

    def get_write_buffer_size(self) -> int:
        return 0  # the explorer IS the backpressure


class ModelReader:
    __slots__ = ("link",)

    def __init__(self, link: Link):
        self.link = link

    async def read(self, n: int = -1) -> bytes:
        while True:
            link = self.link
            if link.inbox:
                take = len(link.inbox) if n < 0 else min(n, len(link.inbox))
                data = bytes(link.inbox[:take])
                del link.inbox[:take]
                link.net.progress += 1
                return data
            if link.eof:
                link.net.progress += 1
                return b""
            fut = asyncio.get_running_loop().create_future()
            link._waiter = fut
            await fut


class ModelWriter:
    """StreamWriter stand-in: writes into the conn's outgoing link;
    ``close()`` closes the WHOLE connection (both directions), like a
    socket close."""

    __slots__ = ("conn", "out", "transport")

    def __init__(self, conn: "ModelConn", out: Link):
        self.conn = conn
        self.out = out
        self.transport = ModelTransport(conn)

    def write(self, data: bytes) -> None:
        self.out.write(data)

    async def drain(self) -> None:
        return

    def close(self) -> None:
        # a dropped conn is a torn-down socket: in-flight frames are
        # gone and both readers EOF now (keeping "gracefully closed"
        # conns deliverable forever would let a frame outlive the
        # connection that carried it — a wire no TCP provides)
        self.conn.kill()

    def is_closing(self) -> bool:
        return self.conn.closed


class ModelConn:
    """One logical connection: links fwd (dialer→target) + rev."""

    __slots__ = ("cid", "dialer", "target", "fwd", "rev", "closed")

    def __init__(self, cid: str, dialer: str, target: str, net: "Network"):
        self.cid = cid
        self.dialer = dialer
        self.target = target
        self.fwd = Link(f"{cid}/fwd", net)
        self.rev = Link(f"{cid}/rev", net)
        self.closed = False

    def link(self, direction: str) -> Link:
        return self.fwd if direction == "fwd" else self.rev

    def kill(self) -> None:
        self.closed = True
        self.fwd.kill()
        self.rev.kill()


class Network:
    """Instance registry + conn table + the dial seam.

    Instances register under their ADVERTISED address string; a model
    dial either fails instantly (unknown / crashed / partitioned — the
    OSError the dial state machine's backoff path expects) or creates a
    ModelConn and schedules the target cluster's real ``_accept`` with
    the passive-side endpoints."""

    def __init__(self):
        self.instances: dict[str, object] = {}  # addr str -> Instance
        self.conns: dict[str, ModelConn] = {}
        self._conn_seq: dict[tuple[str, str], int] = {}
        self.partitions: set[frozenset] = set()  # {group, group}
        self.progress = 0
        self.accept_tasks: list[asyncio.Task] = []

    def register(self, addr_str: str, instance) -> None:
        self.instances[addr_str] = instance

    def partitioned(self, group_a: str, group_b: str) -> bool:
        return (
            group_a != group_b
            and frozenset((group_a, group_b)) in self.partitions
        )

    def connect_fn(self, dialer_instance):
        async def connect(addr):
            target = self.instances.get(str(addr))
            if (
                target is None
                or not target.alive
                or self.partitioned(dialer_instance.group, target.group)
            ):
                raise OSError(f"model: {addr} unreachable")
            pair = (dialer_instance.key, target.key)
            seq = self._conn_seq.get(pair, 0) + 1
            self._conn_seq[pair] = seq
            cid = f"{pair[0]}>{pair[1]}#{seq}"
            conn = ModelConn(cid, *pair, self)
            self.conns[cid] = conn
            # the passive side runs the REAL accept/read-loop code
            task = asyncio.get_running_loop().create_task(
                target.cluster._accept(
                    ModelReader(conn.fwd), ModelWriter(conn, conn.rev)
                )
            )
            self.accept_tasks.append(task)
            return ModelReader(conn.rev), ModelWriter(conn, conn.fwd)

        return connect

    def kill_between(self, group_a: str, group_b: str) -> None:
        for conn in self.conns.values():
            ga = self.instances_group(conn.dialer)
            gb = self.instances_group(conn.target)
            if {ga, gb} == {group_a, group_b} or (
                group_a == group_b and ga == gb == group_a
            ):
                conn.kill()

    def instances_group(self, instance_key: str) -> str:
        for inst in self.instances.values():
            if inst.key == instance_key:
                return inst.group
        return instance_key

    def kill_of_group(self, group: str) -> None:
        """Every conn touching a crashed group dies abortively."""
        for conn in self.conns.values():
            if group in (
                self.instances_group(conn.dialer),
                self.instances_group(conn.target),
            ):
                conn.kill()

    def gc_conns(self) -> None:
        """Forget conns that are dead AND drained on both sides — keeps
        the action space and the state hash from growing with history."""
        for cid in [
            c
            for c, conn in self.conns.items()
            if conn.closed and conn.fwd.eof and conn.rev.eof
        ]:
            del self.conns[cid]
        self.accept_tasks = [t for t in self.accept_tasks if not t.done()]
