"""jmodel: bounded explicit-state exploration of the cluster protocol.

jlint pass 10 (the protocol atlas) pins what the protocol *is*; this
package exhaustively explores what it *does*. It drives the REAL
``jylis_tpu.cluster.Cluster`` handler code — dial state machine,
handshake, read loop, every message handler, the sync-serve machinery,
the held queue, and the lane bus/bridge — over an in-memory
deterministic network (``net.py``): a virtual clock that advances only
when the explorer says so, and an in-memory pipe transport injected
through the ``clock=`` / ``connect=`` seams ``Cluster`` grew for
exactly this. Nothing in the protocol is re-modelled; the only
substitutions are the wall clock, the TCP socket, and the Database
(a minimal host-side GCOUNT lattice speaking the real wire codec —
``world.ModelDatabase``).

The explorer (``explore.py``) enumerates delivery schedules — reorder
across connections, drop (connection kill), duplicate, partition,
crash-reboot-from-journal — over 2-node, 3-node and 2-lane-bus
configurations to a bounded depth, with state-hash deduplication and a
sleep-set partial-order reduction (independent actions on distinct
receiving instances are explored in one order, not all orders).
Invariants checked at every distinct state:

* lattice monotonicity — no (key, replica) cell ever regresses;
* held-queue FIFO order + bounded eviction accounting;
* dial-backoff boundedness and monotonicity up to the cap;

and at quiescence (deliver everything, heal everything, tick until
stable):

* digest match on every replica (nodes or lanes — the convergence
  guarantee the periodic digest exchange promises);
* no stranded rtt stamps (every Pong-soliciting send on a live conn
  eventually matched);
* no in-flight or held frames left.

A violation serialises as a MINIMIZED schedule file (ddmin over the
action trace) that replays as a regression test: the committed corpus
lives in ``tests/model/`` and ``tests/test_model.py`` replays it in
tier-1. ``make model-smoke`` (part of ``make ci``) runs the bounded
exploration against the recorded state floor and time budget in
``scripts/jlint/budget.json``; the full-depth exploration runs behind
``-m soak``.

Run ``python -m scripts.jmodel --help`` for the CLI.
"""

from __future__ import annotations

import contextlib

# Exploration-scale protocol periods: the real constants (50-tick sync
# period, 10-tick cooldown) put interesting behaviour hundreds of
# actions deep — far past any bounded-depth frontier. Shrinking the
# PERIODS (never the logic) is standard model-checking practice: every
# guard still compares the same quantities, only the windows are
# shorter. The patch is scoped and restored, and replay files embed it
# implicitly via the config name.
MODEL_PERIODS = {
    "SYNC_PERIOD_TICKS": 4,
    "SYNC_REQUEST_COOLDOWN": 2,
    "ANNOUNCE_EVERY": 2,
    "IDLE_TICKS_LIMIT": 6,
}


@contextlib.contextmanager
def model_periods():
    """Scope the shrunk protocol periods over a model run."""
    from jylis_tpu.cluster import cluster as cluster_mod

    saved = {k: getattr(cluster_mod, k) for k in MODEL_PERIODS}
    try:
        for k, v in MODEL_PERIODS.items():
            setattr(cluster_mod, k, v)
        yield cluster_mod
    finally:
        for k, v in saved.items():
            setattr(cluster_mod, k, v)
