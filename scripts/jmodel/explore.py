"""Bounded DFS over delivery schedules with dedup + sleep sets.

The explorer cannot snapshot a live asyncio world, so the search is
replay-based: a state IS its action trace from the initial state, and
visiting a state replays the trace into a fresh :class:`~.world.World`
(determinism makes that sound — the same trace always lands on the
same state). Two reductions keep the frontier tractable:

* **state-hash dedup** — ``World.state_hash()`` canonicalises all
  protocol-relevant state; a hash seen before prunes the subtree
  (different interleavings that commute collapse here);
* **sleep sets** — when actions ``a`` and ``b`` touch disjoint node
  groups they commute (a delivery mutates only its receiver + appends
  to that receiver's own outboxes), so after exploring ``a`` first the
  ``b``-subtree carries ``a`` in its sleep set and never re-fires it
  immediately — the classic partial-order reduction, sound because the
  independence relation is conservative (structural actions — kill,
  crash, partition, heal — are dependent with everything).

Invariants run at every DISTINCT state; the expensive global check
(``World.quiesce()``: heal + run to fixpoint + digest match everywhere)
runs at a deterministic sample of depth-bound leaves. A violation
raises out of the search with its trace, which :func:`minimize` shrinks
ddmin-style (drop actions while the same invariant still fails) into a
schedule file — the replayable regression artifact committed under
``tests/model/``.

Callers must hold :func:`scripts.jmodel.model_periods` open around any
exploration or replay — schedules are defined against the shrunk
protocol periods.
"""

from __future__ import annotations

from dataclasses import dataclass

from .world import Runtime, Violation, World

SCHEDULE_SCHEMA = 1


class _Found(Exception):
    def __init__(self, trace, violation: Violation):
        super().__init__(str(violation))
        self.trace = list(trace)
        self.violation = violation


class _Done(Exception):
    pass


@dataclass
class Result:
    config: str
    depth: int
    states: int = 0
    leaves: int = 0
    quiesced: int = 0
    violation: dict | None = None
    schedule: dict | None = None
    capped: bool = False


def _group_of(instance_key: str) -> str:
    return instance_key.split(".", 1)[0]


def _touched(action: tuple) -> frozenset | None:
    """Node groups an action can affect, or None for structural actions
    (dependent with everything)."""
    kind = action[0]
    if kind == "tick":
        return frozenset((_group_of(action[1]),))
    if kind in ("deliver", "dup"):
        cid, direction = action[1], action[2]
        dialer, rest = cid.split(">", 1)
        target = rest.split("#", 1)[0]
        recv = target if direction == "fwd" else dialer
        return frozenset((_group_of(recv),))
    if kind in ("write", "bdec", "mint"):
        # mint flushes + snapshots at the one group, exactly a tick's
        # footprint for the reduction's purposes
        return frozenset((action[1],))
    if kind == "bxfer":
        # mutates only the SENDER's lattice (the receiver learns of the
        # credit when the delta delivers, which is its own action)
        return frozenset((action[1],))
    return None  # kill / crash / part / heal


def independent(a: tuple, b: tuple) -> bool:
    ta, tb = _touched(a), _touched(b)
    return ta is not None and tb is not None and ta.isdisjoint(tb)


class Explorer:
    def __init__(
        self,
        config: str,
        depth: int,
        budgets: dict | None = None,
        quiesce_every: int = 16,
        max_states: int | None = None,
        escrow_unsafe: bool = False,
        session_unsafe: bool = False,
        bridge_unsafe: bool = False,
    ):
        self.config = config
        self.depth = depth
        self.budgets = budgets
        self.quiesce_every = quiesce_every
        self.max_states = max_states
        # arms the deliberately broken BCOUNT transfer rule (world.py):
        # the exploration is then EXPECTED to find an invariant
        # violation — the counterexample demonstration
        self.escrow_unsafe = escrow_unsafe
        # ... and the broken session-watermark rule (sessions.py unsafe
        # mode): the session_ryw counterexample demonstration
        self.session_unsafe = session_unsafe
        # ... and the broken bridge-demotion rule (never demote — the
        # pre-failover v10 behavior): the bridge_demotion stale-bridge
        # counterexample demonstration (PR 15)
        self.bridge_unsafe = bridge_unsafe
        self.visited: set[str] = set()
        self.leaves = 0
        self.quiesced = 0
        self._runtime: Runtime | None = None

    def _replay(self, trace) -> World:
        world = World(self.config, self.budgets, runtime=self._runtime,
                      escrow_unsafe=self.escrow_unsafe,
                      session_unsafe=self.session_unsafe,
                      bridge_unsafe=self.bridge_unsafe)
        try:
            for action in trace:
                applied = world.apply(tuple(action))
                assert applied, f"replay of own trace lost {action}"
            return world
        # jlint: broad-ok — cleanup-and-reraise: the world (its tasks
        # parked on the shared runtime loop) must be torn down on ANY
        # failure, including KeyboardInterrupt, before propagating
        except BaseException:
            world.close()
            raise

    def run(self) -> Result:
        result = Result(self.config, self.depth)
        self._runtime = Runtime()
        try:
            self._dfs([], frozenset())
        except _Found as f:
            result.violation = {
                "invariant": f.violation.name,
                "detail": f.violation.detail,
            }
            minimized = minimize(
                self.config, f.trace, f.violation.name, self.budgets,
                runtime=self._runtime, escrow_unsafe=self.escrow_unsafe,
                session_unsafe=self.session_unsafe,
                bridge_unsafe=self.bridge_unsafe,
            )
            result.schedule = schedule_dict(
                self.config, minimized, expect=f.violation.name,
                note=f.violation.detail, escrow_unsafe=self.escrow_unsafe,
                session_unsafe=self.session_unsafe,
                bridge_unsafe=self.bridge_unsafe,
                budgets=self.budgets,
            )
        except _Done:
            result.capped = True
        finally:
            self._runtime.close()
        result.states = len(self.visited)
        result.leaves = self.leaves
        result.quiesced = self.quiesced
        return result

    def _dfs(self, trace: list, sleep: frozenset, world=None) -> None:
        """Visit the state `trace` lands on. ``world`` may carry the
        already-positioned World (first-child descent hands its own
        down, saving one full replay per internal node); ownership
        transfers — this frame closes it or hands it on."""
        if world is None:
            world = self._replay(trace)
        actions = None
        try:
            h = world.state_hash()
            if h in self.visited:
                return
            self.visited.add(h)
            if self.max_states and len(self.visited) >= self.max_states:
                raise _Done
            try:
                world.check_invariants()
            except Violation as v:
                raise _Found(trace, v) from None
            if len(trace) >= self.depth:
                self.leaves += 1
                # quiesce a deterministic sample of leaves (plus always
                # the first): the global laws are expensive — a fixpoint
                # run per leaf would dwarf the search itself
                if (self.leaves - 1) % self.quiesce_every == 0:
                    self.quiesced += 1
                    try:
                        world.quiesce()
                    except Violation as v:
                        raise _Found(trace + [("quiesce",)], v) from None
                return
            actions = [
                a for a in (tuple(x) for x in world.enabled_actions())
                if a not in sleep
            ]
        finally:
            if actions is None:
                world.close()
        explored: list[tuple] = []
        for i, action in enumerate(actions):
            child_sleep = frozenset(
                other
                for other in (set(sleep) | set(explored))
                if independent(other, action)
            )
            if i == 0:
                # descend in place: this world becomes the first child's
                try:
                    applied = world.apply(action)
                    assert applied, f"frontier action {action} not enabled"
                # jlint: broad-ok — cleanup-and-reraise before handing
                # the world down (same teardown contract as _replay)
                except BaseException:
                    world.close()
                    raise
                self._dfs(trace + [action], child_sleep, world=world)
            else:
                self._dfs(trace + [action], child_sleep)
            explored.append(action)
        if not actions:
            world.close()


# ---- schedules (the replayable counterexample artifact) ---------------------


def schedule_dict(
    config: str, actions, expect: str = "pass", note: str = "",
    escrow_unsafe: bool = False, session_unsafe: bool = False,
    bridge_unsafe: bool = False, budgets: dict | None = None,
) -> dict:
    out = {
        "schema": SCHEDULE_SCHEMA,
        "config": config,
        "actions": [list(a) for a in actions],
        # "pass" = regression corpus entry (the defect this schedule
        # found is fixed; replay must hold every invariant). An
        # invariant name = a live counterexample under triage.
        "expect": expect,
        "note": note,
    }
    if escrow_unsafe:
        # the schedule only fails against the deliberately broken
        # escrow rule; the replayer must re-arm it
        out["escrow_unsafe"] = True
    if session_unsafe:
        # likewise for the broken session-watermark rule
        out["session_unsafe"] = True
    if bridge_unsafe:
        # likewise for the broken bridge-demotion rule
        out["bridge_unsafe"] = True
    if budgets:
        # non-default budgets are part of the counterexample: without
        # them a standalone replay silently skips now-disabled actions
        # and degrades to a weaker test
        out["budgets"] = dict(budgets)
    return out


def replay_schedule(
    data: dict, budgets: dict | None = None, runtime: Runtime | None = None
):
    """Replay one schedule file's actions; returns the Violation hit,
    or None if every invariant held. Actions that are no longer enabled
    (the protocol moved on under the schedule) are skipped — a schedule
    degrades to a weaker test, never a spurious failure."""
    if data.get("schema") != SCHEDULE_SCHEMA:
        raise ValueError(f"unknown schedule schema: {data.get('schema')!r}")
    world = World(data["config"], budgets or data.get("budgets"),
                  runtime=runtime,
                  escrow_unsafe=bool(data.get("escrow_unsafe")),
                  session_unsafe=bool(data.get("session_unsafe")),
                  bridge_unsafe=bool(data.get("bridge_unsafe")))
    try:
        explicit_quiesce = False
        for raw in data["actions"]:
            action = tuple(tuple(x) if isinstance(x, list) else x for x in raw)
            if action == ("quiesce",):
                explicit_quiesce = True
                world.quiesce()
            else:
                world.apply(action)
                world.check_invariants()
        if not explicit_quiesce:
            world.quiesce()
        return None
    except Violation as v:
        return v
    finally:
        world.close()


def minimize(
    config: str, trace: list, expect: str, budgets: dict | None = None,
    rounds: int = 4, runtime: Runtime | None = None,
    escrow_unsafe: bool = False, session_unsafe: bool = False,
    bridge_unsafe: bool = False,
) -> list:
    """ddmin-lite over the action trace: greedily drop actions while
    replaying still hits the SAME invariant. Replays are cheap at
    counterexample depth; the result is what a human debugs and what
    the corpus replays forever."""

    def still_fails(candidate) -> bool:
        data = {
            "schema": SCHEDULE_SCHEMA,
            "config": config,
            "actions": [list(a) for a in candidate],
        }
        if escrow_unsafe:
            data["escrow_unsafe"] = True
        if session_unsafe:
            data["session_unsafe"] = True
        if bridge_unsafe:
            data["bridge_unsafe"] = True
        v = replay_schedule(data, budgets, runtime=runtime)
        return v is not None and v.name == expect

    current = [tuple(a) for a in trace]
    for _ in range(rounds):
        shrunk = False
        i = len(current) - 1
        while i >= 0:
            candidate = current[:i] + current[i + 1:]
            if still_fails(candidate):
                current = candidate
                shrunk = True
            i -= 1
        if not shrunk:
            break
    return current
