"""jmodel CLI: `python -m scripts.jmodel` (what `make model-smoke` runs).

Modes:

* ``--smoke`` — the per-commit gate: bounded exploration of all three
  configurations (2-node, 3-node, 2-lane-bus) at the committed depths,
  asserting every invariant AND the recorded coverage floor
  (``model_min_states`` in scripts/jlint/budget.json — a refactor that
  silently collapses the explored space fails loudly). ``--budget``
  additionally enforces ``model_budget_seconds`` (exit 3 on breach),
  exactly like jlint's lint budget.
* ``--config NAME --depth N`` — one exploration, tunable (the soak
  tier runs deeper via tests/test_model.py).
* ``--replay FILE`` — replay one schedule file; exit 0 if every
  invariant holds (the regression expectation), 1 otherwise.

A violation found in any mode serialises its MINIMIZED schedule to
``jmodel_counterexample.json``: triage it, fix the defect, then commit
the schedule under ``tests/model/`` with ``"expect": "pass"`` so the
fix replays forever (the PR 3 / PR 7 found-defect discipline).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from . import model_periods
from .explore import Explorer, replay_schedule
from .world import CONFIG_NAMES

BUDGET_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "jlint", "budget.json",
)

# committed smoke parameters (depth, quiesce-every): deep enough that
# the four frontiers together clear the recorded model_min_states
# floor (budget.json), shallow enough for the per-commit budget. The
# v10 sessions/regions axes (a mint action per group, the regions3
# config with its bridge relays and session invariants) grow the
# frontier again on top of v9's bdec/bxfer growth; the soak tier
# (tests/test_model.py -m soak) goes deeper on every axis.
# nodes2 drops from depth 6 to 5 with the v10 mint axis: the sessions
# action roughly doubled its per-depth branching, and depth 6 alone ran
# 112k states / 305s — past the whole budget. Depth 5 keeps the config
# at ~23k states while the three NEW-coverage configs (lane bus,
# regions, plus nodes3's gossip discovery) spend the rest of the box.
SMOKE_PARAMS = {
    "nodes2": (5, 24),
    "nodes3": (4, 16),
    "lanes2": (4, 16),
    "regions3": (4, 16),
}

COUNTEREXAMPLE_PATH = "jmodel_counterexample.json"


def _load_budget() -> dict:
    try:
        with open(BUDGET_PATH, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _run_one(config: str, depth: int, quiesce_every: int) -> "Result":
    ex = Explorer(config, depth, quiesce_every=quiesce_every)
    t0 = time.perf_counter()
    result = ex.run()
    dt = time.perf_counter() - t0
    print(
        f"jmodel: {config} depth {depth}: {result.states} distinct states, "
        f"{result.leaves} leaves ({result.quiesced} quiesced) in {dt:.1f}s"
    )
    return result


def _report_violation(result) -> None:
    v = result.violation
    print(
        f"jmodel: INVARIANT VIOLATED in {result.config}: "
        f"{v['invariant']} — {v['detail']}",
        file=sys.stderr,
    )
    with open(COUNTEREXAMPLE_PATH, "w", encoding="utf-8") as f:
        json.dump(result.schedule, f, indent=1)
        f.write("\n")
    print(
        f"jmodel: minimized schedule ({len(result.schedule['actions'])} "
        f"actions) written to {COUNTEREXAMPLE_PATH} — fix the defect, "
        "then commit it under tests/model/ with expect=pass",
        file=sys.stderr,
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="jmodel")
    ap.add_argument("--config", choices=CONFIG_NAMES)
    ap.add_argument("--depth", type=int, default=5)
    ap.add_argument(
        "--quiesce-every", type=int, default=16,
        help="run the full quiescence check on every Nth depth-bound leaf",
    )
    ap.add_argument("--smoke", action="store_true",
                    help="bounded exploration of all configs + coverage floor")
    ap.add_argument("--budget", action="store_true",
                    help="fail (exit 3) past model_budget_seconds")
    ap.add_argument("--replay", metavar="FILE",
                    help="replay one schedule file")
    args = ap.parse_args(argv)

    if args.replay:
        with open(args.replay, encoding="utf-8") as f:
            data = json.load(f)
        with model_periods():
            violation = replay_schedule(data)
        if violation is None:
            print(f"jmodel: replay {args.replay}: all invariants hold")
            return 0
        print(f"jmodel: replay {args.replay}: {violation}", file=sys.stderr)
        return 1

    t0 = time.perf_counter()
    results = []
    with model_periods():
        if args.smoke:
            for config, (depth, quiesce_every) in SMOKE_PARAMS.items():
                results.append(_run_one(config, depth, quiesce_every))
                if results[-1].violation:
                    break
        elif args.config:
            results.append(
                _run_one(args.config, args.depth, args.quiesce_every)
            )
        else:
            ap.error("one of --smoke / --config / --replay is required")
    total_states = sum(r.states for r in results)
    total_s = time.perf_counter() - t0

    for r in results:
        if r.violation:
            _report_violation(r)
            return 1

    rc = 0
    if args.smoke:
        budget = _load_budget()
        floor = budget.get("model_min_states")
        print(
            f"jmodel: smoke total {total_states} distinct states across "
            f"{len(results)} configs in {total_s:.1f}s"
            + (f" (floor {floor})" if floor else "")
        )
        if floor and total_states < floor:
            print(
                f"jmodel: COVERAGE COLLAPSED — {total_states} states < "
                f"recorded floor {floor} (scripts/jlint/budget.json). A "
                "protocol or explorer change shrank the reachable space; "
                "understand why before re-recording.",
                file=sys.stderr,
            )
            rc = 1
        bound = budget.get("model_budget_seconds")
        if args.budget and bound and total_s > bound:
            print(
                f"jmodel: BUDGET EXCEEDED — {total_s:.1f}s > {bound:.1f}s "
                "(scripts/jlint/budget.json model_budget_seconds)",
                file=sys.stderr,
            )
            rc = rc or 3
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
