"""The explorable world: real Clusters over the model network.

A ``World`` is one configuration (2-node, 3-node, or 2-lane-bus) of
REAL ``Cluster`` instances wired to ``net.py``'s in-memory transport
and virtual clock, each over a ``ModelDatabase`` — a minimal host-side
GCOUNT lattice (pointwise-max join, the paper's canonical delta CRDT)
that speaks the real wire codec, so every frame the explorer reorders
is a genuine schema-v6 frame through the genuine framing/CRC/codec
path.

The explorer talks to the world through three methods:

* ``enabled_actions()`` — the deterministic, stably-ordered action
  frontier: deliveries per link, heartbeat ticks per instance, bounded
  duplicates / connection kills / partitions / crash-reboots / extra
  client writes;
* ``apply(action)`` — fire one action, then settle the event loop to
  idle (every task parked on a model-network future);
* ``state_hash()`` — canonical digest of ALL protocol-relevant state
  (lattices, membership, conn/dial/sync machine fields, link contents,
  remaining budgets), timestamps rank-normalised so the virtual clock's
  absolute values never defeat deduplication.

Invariants: ``check_invariants()`` runs the cheap per-state laws
(lattice monotonicity, held-queue FIFO + bound, dial-backoff
boundedness/monotonicity) after every action; ``quiesce()`` heals
everything, drives the system to a fixpoint and asserts the global
laws (digest match everywhere, no stranded rtt stamps, nothing in
flight). A failure raises :class:`Violation` carrying the invariant
name — the explorer turns that plus its action trace into a minimized
schedule file.
"""

from __future__ import annotations

import asyncio
import hashlib
import selectors
import struct
from concurrent.futures import ThreadPoolExecutor

from jylis_tpu import sessions as sessions_mod
from jylis_tpu.cluster import cluster as cluster_mod
from jylis_tpu.cluster.cluster import Cluster
from jylis_tpu.lanes import wire_bridge
from jylis_tpu.obs.registry import MetricsRegistry
from jylis_tpu.ops import compose
from jylis_tpu.ops.bcount import BCount
from jylis_tpu.ops.tensor_host import Tensor, okey_u32
from jylis_tpu.utils.address import Address
from jylis_tpu.utils.config import Config
from jylis_tpu.utils.log import Log

from .net import Network, VirtualClock

CONFIG_NAMES = ("nodes2", "nodes3", "lanes2", "regions3")

TICK_MS = 100  # virtual ms per heartbeat action

# per-trace budgets for the expensive/structural actions: unbounded,
# each would multiply the frontier at every depth for little new
# coverage (state-hash dedup already collapses the repeats)
DEFAULT_BUDGETS = {
    "writes": 1,  # extra client writes per group (on top of the seed write)
    "dups": 1,
    "kills": 1,
    "crashes": 1,
    "partitions": 1,
    # BCOUNT contention (schema v9): escrow-checked decrements per group
    # and escrow transfers OUT of the seed-escrow group (global) — the
    # schedules the `0 <= value <= bound` invariant must survive
    "bdecs": 1,
    "bxfers": 1,
    # session tokens (schema v10): SESSION TOKEN mints per group — each
    # snapshots the group's vector + its own-column floor, and the
    # read-your-writes invariant then holds at EVERY later state: any
    # replica whose vector dominates the token must show the floor
    "mints": 1,
    # bridge failover (PR 15, regions3 only): bkill takes a group DOWN
    # and LEAVES it down (unlike crash's immediate reboot) so the
    # schedules between the kill and the matching breboot — exactly
    # where liveness demotion, succession, and the dual-bridge overlap
    # live — are explorable; quiesce reboots any still-down group
    # before asserting convergence
    "bkills": 1,
}

# the demotion threshold every model Cluster runs with (small enough
# that directed schedules reach a handover within a few tick actions);
# the bridge_demotion invariant checks observers against THIS value
# even when bridge_unsafe arms the broken never-demote rule
BRIDGE_DEMOTE_MODEL = 6

# the modelled bounded counter: one key, bound granted (and matching
# dec-escrow minted via incs) by the rid-1 replica's row — a CONVERGED
# initial state every replica boots with, so the contended resource
# exists before any schedule runs. Other replicas can decrement only
# after an escrow transfer reaches them: exactly the interplay the
# exploration must cover.
BCOUNT_KEY = b"q"
BCOUNT_SEED_RID = 1
BCOUNT_BOUND = 2


def _seed_bcount() -> BCount:
    bc = BCount()
    bc.grants[BCOUNT_SEED_RID] = BCOUNT_BOUND
    bc.incs[BCOUNT_SEED_RID] = BCOUNT_BOUND
    return bc


class Violation(Exception):
    """One invariant broke. ``name`` is the invariant's stable id."""

    def __init__(self, name: str, detail: str):
        super().__init__(f"{name}: {detail}")
        self.name = name
        self.detail = detail


class ModelDatabase:
    """Host-side GCOUNT + TENSOR lattices with the exact Database
    surface the Cluster consumes, producing real codec-shaped deltas.
    GCOUNT is the scalar delta payload; TENSOR (element-wise-max mode,
    dim-2 vectors — ops/tensor_host.Tensor, the REAL wire object) makes
    every explored schedule also carry a non-scalar binary payload over
    the bus and bridge. One ``write`` action mutates both lattices (the
    tensor cell is a deterministic function of the counter write), so
    the frontier does not grow a second write axis. ``journal`` is the
    WAL analog: local writes survive a crash-reboot (the tensor write
    re-derives from the journaled counter), converged remote state does
    not (it heals back over the rejoin sync — the exact path worth
    exploring)."""

    DATA_TYPES = ("GCOUNT", "TENSOR", "MAP", "BCOUNT")

    def __init__(self, name: str, rid: int, journal=None,
                 escrow_unsafe: bool = False, session_unsafe: bool = False):
        self.name = name
        self.rid = rid
        self.escrow_unsafe = escrow_unsafe
        # the node's applied-interval vector (jylis_tpu/sessions.py —
        # the REAL object, bound by the real Cluster exactly like the
        # product's Database). session_unsafe arms the deliberately
        # broken watermark rule (first-observed jump) the explorer must
        # refute with a minimized counterexample.
        self.sessions = sessions_mod.SessionIndex(unsafe=session_unsafe)
        self.state: dict[bytes, dict[int, int]] = {}
        self.state_t: dict[bytes, Tensor] = {}
        # MAP (schema v9): real compose.MapCRDT objects, keyed per map
        # key; wire batches carry packed (key, field) composites exactly
        # like the product. One write action edits a per-rid field (a
        # deterministic function of the counter write, so the frontier
        # grows no new axis and the WAL replay re-derives it).
        self.state_m: dict[bytes, compose.MapCRDT] = {}
        # BCOUNT (schema v9): real ops/bcount.BCount states; every
        # replica boots with the SAME converged seed (the bound + the
        # rid-1 escrow), so `0 <= value <= bound` is at stake from the
        # first action
        self.state_b: dict[bytes, BCount] = {BCOUNT_KEY: _seed_bcount()}
        self.pending: list[tuple[bytes, dict[int, int]]] = []
        self.write_seq = 0  # own-write ordinal (drives WRITE_KEYS)
        # own counter columns that have been FLUSHED under THIS
        # incarnation (absolute values — state-based columns subsume
        # earlier writes): exactly the cells a token minted now covers.
        # Journal-replayed state is deliberately NOT here — a reboot
        # forgets its shipped history, and a fresh token must not claim
        # writes only the OLD incarnation's (possibly lost) stream or a
        # digest sync can deliver (the product contract: clients retain
        # their token across writes; docs/sessions.md).
        self.own_shipped: dict[bytes, int] = {}
        self.pending_t: list[tuple[bytes, Tensor]] = []
        self.pending_m: list[tuple[bytes, tuple]] = []
        self.pending_b: list[tuple[bytes, tuple]] = []
        self.refused_decs = 0  # OUTOFBOUND analog: local-rights refusals
        # WAL entries are tagged ops now that two kinds exist:
        # ("w", key, n) counter writes (tensor + MAP edits re-derive),
        # and ("bstate", wire) — the POST-MUTATION full per-key BCOUNT
        # view, replayed by unconditional converge. This mirrors the
        # product exactly: its journal stores the flushed full-view
        # delta and its replay converges it back — replay NEVER re-runs
        # a rights check (a journaled spend whose funding had arrived
        # over the network before the crash must not vanish because the
        # seed state alone cannot fund it; review fix).
        self.journal: list[tuple] = list(journal or ())
        self.metrics = MetricsRegistry()
        for entry in self.journal:  # boot replay (all lattices)
            if entry[0] == "w":
                _, key, n = entry
                rows = self.state.setdefault(key, {})
                rows[self.rid] = max(rows.get(self.rid, 0), n)
                self._tensor_join(key, self._tensor_delta(n))
                self._map_edit(key, n)
            elif entry[0] == "bstate":
                self.state_b[BCOUNT_KEY].converge(
                    BCount.from_wire(entry[1])
                )

    def _tensor_delta(self, n: int) -> Tensor:
        # a function of (rid, counter value): replayable from the WAL
        return Tensor.max_value(struct.pack("<2f", float(self.rid), float(n)))

    def _tensor_join(self, key: bytes, delta: Tensor) -> None:
        cur = self.state_t.get(key)
        if cur is None:
            cur = Tensor()
            self.state_t[key] = cur
        cur.converge(delta)

    def _map_edit(self, key: bytes, n: int) -> tuple[bytes, tuple]:
        """The MAP face of a counter write: bump a GCOUNT-valued field
        owned by this rid in map key ``m``. Returns the decomposed
        (packed composite, full field unit) delta entry."""
        m = self.state_m.setdefault(b"m", compose.MapCRDT())
        field = b"f%d" % self.rid
        m.set_field(field, self.rid, "GCOUNT", [b"1"])
        packed = compose.pack_field(b"m", field)
        return (packed, m.fields[field].unit())

    def _bcount_transfer(self, to_rid: int) -> bool:
        """Move one unit of dec-escrow to another replica."""
        return self.state_b[BCOUNT_KEY].transfer(self.rid, to_rid, 1, "DEC")

    # write keys cycle per own-write ordinal: distinct keys are what
    # makes a lost-frame gap OBSERVABLE (absolute counter columns
    # subsume earlier writes to the SAME key, so a one-key model could
    # never exhibit the session hole the unsafe watermark rule hides)
    WRITE_KEYS = (b"x", b"y", b"z", b"w", b"v")

    def local_write(self, key: bytes | None = None) -> None:
        if key is None:
            key = self.WRITE_KEYS[min(self.write_seq, 4)]
        self.write_seq += 1
        rows = self.state.setdefault(key, {})
        n = rows.get(self.rid, 0) + 1
        rows[self.rid] = n
        self.journal.append(("w", key, n))  # WAL before the network sees it
        self.pending.append((key, {self.rid: n}))
        t = self._tensor_delta(n)
        self._tensor_join(key, t)
        self.pending_t.append((key, t))
        self.pending_m.append(self._map_edit(key, n))

    def local_bdec(self) -> bool:
        """One escrow-checked decrement; a refusal (insufficient local
        dec-escrow — the RESP surface's OUTOFBOUND) changes no lattice
        state and is counted. In escrow_unsafe mode the DELIBERATELY
        BROKEN rule ships: the local rights check is skipped (the
        canonical escrow bug — spending without owning the right), and
        the explorer must surface it as a minimized `value < 0`
        counterexample schedule."""
        bc = self.state_b[BCOUNT_KEY]
        if self.escrow_unsafe:
            bc.decs[self.rid] = bc.decs.get(self.rid, 0) + 1
        elif not bc.dec(self.rid, 1):
            self.refused_decs += 1
            return False
        self.journal.append(("bstate", bc.to_wire()))
        self.pending_b.append((BCOUNT_KEY, bc.to_wire()))
        return True

    def local_bxfer(self, to_rid: int) -> bool:
        if not self._bcount_transfer(to_rid):
            self.refused_decs += 1
            return False
        wire = self.state_b[BCOUNT_KEY].to_wire()
        self.journal.append(("bstate", wire))
        self.pending_b.append((BCOUNT_KEY, wire))
        return True

    def _join(self, batch) -> None:
        for key, delta in batch:
            rows = self.state.setdefault(bytes(key), {})
            for rid, v in delta.items():
                if v > rows.get(rid, 0):
                    rows[rid] = v

    async def converge_async(self, deltas) -> None:
        name, batch = deltas
        if name == "GCOUNT":
            self._join(batch)
        elif name == "TENSOR":
            for key, delta in batch:
                self._tensor_join(bytes(key), delta)
        elif name == "MAP":
            for packed, unit in batch:
                key, field = compose.unpack_field(bytes(packed))
                self.state_m.setdefault(
                    key, compose.MapCRDT()
                ).converge_field(field, unit)
        elif name == "BCOUNT":
            for key, wire in batch:
                bc = self.state_b.setdefault(bytes(key), BCount())
                bc.converge(BCount.from_wire(wire))

    async def flush_deltas_async(self, fn) -> None:
        if self.pending:
            batch, self.pending = self.pending, []
            for key, delta in batch:
                n = delta.get(self.rid, 0)
                if n > self.own_shipped.get(key, 0):
                    self.own_shipped[key] = n
            fn(("GCOUNT", tuple(batch)))
        if self.pending_t:
            batch_t, self.pending_t = self.pending_t, []
            fn(("TENSOR", tuple(batch_t)))
        if self.pending_m:
            batch_m, self.pending_m = self.pending_m, []
            fn(("MAP", tuple(batch_m)))
        if self.pending_b:
            batch_b, self.pending_b = self.pending_b, []
            fn(("BCOUNT", tuple(batch_b)))

    async def sync_type_digests_async(self) -> tuple[bytes, ...]:
        return (self._digest_g(), self._digest_t(), self._digest_m(),
                self._digest_b())

    # ---- schema-v8 range tier (the real Database's digest-tree API) ----

    @staticmethod
    def _bucket(key: bytes) -> int:
        # the product's sync_bucket (models/database.py): sha256(key)[0]
        return hashlib.sha256(key).digest()[0]

    def _key_hashes(self, name: str):
        """(key, canonical per-key hash) pairs — converged replicas
        produce identical pairs, so leaf digests compare across nodes
        exactly like the real incremental tree."""
        if name == "GCOUNT":
            for k, rows in self.state.items():
                if rows:
                    yield k, hashlib.sha256(
                        b"G\x00" + k + repr(sorted(rows.items())).encode()
                    ).digest()
        elif name == "TENSOR":
            for k, t in self.state_t.items():
                if t.mode != 0:
                    yield k, hashlib.sha256(
                        b"T\x00" + k + repr(t.canon()).encode()
                    ).digest()
        elif name == "MAP":
            # composite (key, field) leaves, exactly like the product's
            # digest tree: range repair pulls divergent FIELDS
            for k, m in self.state_m.items():
                for field, f in m.fields.items():
                    packed = compose.pack_field(k, field)
                    yield packed, hashlib.sha256(
                        b"M\x00" + packed + repr(f.canon()).encode()
                    ).digest()
        elif name == "BCOUNT":
            for k, bc in self.state_b.items():
                if not bc.is_bottom():
                    yield k, hashlib.sha256(
                        b"B\x00" + k + repr(bc.canon()).encode()
                    ).digest()

    async def sync_tree_async(self, name: str) -> tuple:
        leaves: dict[int, int] = {}
        for key, h in self._key_hashes(name):
            b = self._bucket(key)
            leaves[b] = leaves.get(b, 0) ^ int.from_bytes(h, "big")
        return tuple(
            (b, v.to_bytes(32, "big"))
            for b, v in sorted(leaves.items())
            if v
        )

    async def dump_range_async(self, name: str, buckets) -> list:
        bset = set(buckets)
        dump = await self.dump_state_async(names=(name,))
        batch = dump[0][1] if dump else []
        return [(k, d) for k, d in batch if self._bucket(k) in bset]

    def _tensor_copy(self, t: Tensor) -> Tensor:
        out = Tensor()
        out.converge(t)
        return out

    async def dump_state_async(self, names=None):
        names = tuple(names) if names is not None else self.DATA_TYPES
        out = []
        for n in names:
            if n == "GCOUNT":
                out.append(
                    (
                        "GCOUNT",
                        [(k, dict(v)) for k, v in sorted(self.state.items())],
                    )
                )
            elif n == "TENSOR":
                # copies: the dump is encoded in a worker thread while
                # actions keep mutating the live lattice objects
                out.append(
                    (
                        "TENSOR",
                        [
                            (k, self._tensor_copy(t))
                            for k, t in sorted(self.state_t.items())
                            if t.mode != 0
                        ],
                    )
                )
            elif n == "MAP":
                out.append(
                    (
                        "MAP",
                        [
                            (compose.pack_field(k, field),
                             m.fields[field].unit())
                            for k, m in sorted(self.state_m.items())
                            for field in sorted(m.fields)
                        ],
                    )
                )
            elif n == "BCOUNT":
                out.append(
                    (
                        "BCOUNT",
                        [
                            (k, bc.to_wire())
                            for k, bc in sorted(self.state_b.items())
                            if not bc.is_bottom()
                        ],
                    )
                )
            elif n == "SYSTEM":
                out.append(("SYSTEM", []))
        return out

    def _digest_g(self) -> bytes:
        canon = sorted(
            (k.hex(), sorted(v.items()))
            for k, v in self.state.items()
            if v
        )
        return hashlib.sha256(repr(canon).encode()).digest()

    def _digest_t(self) -> bytes:
        canon = sorted(
            (k.hex(), t.canon())
            for k, t in self.state_t.items()
            if t.mode != 0
        )
        return hashlib.sha256(repr(canon).encode()).digest()

    def _digest_m(self) -> bytes:
        canon = sorted(
            (k.hex(), m.canon()) for k, m in self.state_m.items()
        )
        return hashlib.sha256(repr(canon).encode()).digest()

    def _digest_b(self) -> bytes:
        canon = sorted(
            (k.hex(), bc.canon())
            for k, bc in self.state_b.items()
            if not bc.is_bottom()
        )
        return hashlib.sha256(repr(canon).encode()).digest()

    def digest(self) -> bytes:
        return hashlib.sha256(
            self._digest_g() + self._digest_t() + self._digest_m()
            + self._digest_b()
        ).digest()

    def cells(self) -> dict[tuple, int]:
        """Per-cell monotonicity floor: counter cells AND tensor
        coordinates (as okey ints — per-coordinate max must never
        regress)."""
        out: dict[tuple, int] = {
            (k, rid): v
            for k, rows in self.state.items()
            for rid, v in rows.items()
        }
        import numpy as np

        for k, t in self.state_t.items():
            if t.mode == 0:
                continue
            # the REAL lattice order (tensor_host.okey_u32), not a copy:
            # the floor must track the product's definition exactly
            keys = okey_u32(np.frombuffer(t.val, "<u4"))
            for i, okey in enumerate(keys.tolist()):
                out[("T", k, i)] = okey
        # MAP: per-field edit counters, tombstone cells, and the inner
        # GCOUNT columns are all monotone
        for k, m in self.state_m.items():
            for field, f in m.fields.items():
                for rid, seq in f.ver.items():
                    out[("Mv", k, field, rid)] = seq
                for rid, seq in f.tomb.items():
                    out[("Mt", k, field, rid)] = seq
                if f.itype == "GCOUNT":
                    for rid, v in f.val.items():
                        out[("Mg", k, field, rid)] = v
        # BCOUNT: every component cell is monotone (the join is
        # pointwise max over all five)
        for k, bc in self.state_b.items():
            for tag, span in (
                ("Bg", bc.grants), ("Bi", bc.incs), ("Bd", bc.decs),
            ):
                for rid, v in span.items():
                    out[(tag, k, rid)] = v
            for tag, mat in (("Bxi", bc.xi), ("Bxd", bc.xd)):
                for (f_, t_), v in mat.items():
                    out[(tag, k, f_, t_)] = v
        return out


class Instance:
    """One Cluster's place in the world. ``group`` is the
    crash/partition granularity (a lane-split node is one group with
    two instances: the bus and the external cluster)."""

    def __init__(self, key: str, group: str, addr: Address):
        self.key = key
        self.group = group
        self.addr = addr
        self.alive = True
        self.cluster: Cluster | None = None
        self.database: ModelDatabase | None = None


class _TrackedExecutor(ThreadPoolExecutor):
    """Single worker + a future ledger: settle() can WAIT on in-flight
    ``to_thread`` work (the sync-dump encodes) instead of racing it —
    one worker keeps completion order = submission order, so the drain
    is deterministic."""

    def __init__(self):
        super().__init__(max_workers=1, thread_name_prefix="jmodel")
        self.futures = []

    def submit(self, fn, /, *args, **kwargs):
        f = super().submit(fn, *args, **kwargs)
        self.futures.append(f)
        return f


class _NullSelector(selectors.BaseSelector):
    """The model loop has no real file descriptors — every wake-up is a
    call_soon from the model network or the executor — so the epoll
    syscall per loop iteration (hundreds of thousands per exploration)
    is pure overhead. `select` parks briefly only when the loop is
    genuinely idle waiting on the executor thread."""

    def __init__(self):
        self._map = {}

    def register(self, fileobj, events, data=None):  # pragma: no cover
        key = selectors.SelectorKey(fileobj, 0, events, data)
        self._map[fileobj] = key
        return key

    def unregister(self, fileobj):  # pragma: no cover
        return self._map.pop(fileobj)

    def select(self, timeout=None):
        if timeout is None or timeout > 0:
            # genuinely idle (waiting on the executor thread): yield the
            # GIL briefly instead of busy-spinning the loop
            import time as _time

            _time.sleep(5e-5)
        return []

    def get_map(self):
        return self._map

    def close(self):
        self._map.clear()


class Runtime:
    """One event loop + tracked executor shared across the thousands of
    short-lived Worlds a replay-based search creates — loop construction
    and teardown would otherwise dominate the whole exploration.

    ``task_events`` counts every task creation AND completion (via a
    task factory): together with the network's progress counter it is
    the O(1) settle fingerprint — ``asyncio.all_tasks()`` walks a
    weakset of every live task and measurably dominated the search."""

    def __init__(self):
        self.loop = asyncio.SelectorEventLoop(_NullSelector())
        self.executor = _TrackedExecutor()
        self.loop.set_default_executor(self.executor)
        self.task_events = 0

        def factory(loop, coro):
            self.task_events += 1
            task = asyncio.Task(coro, loop=loop)
            task.add_done_callback(self._task_done)
            return task

        self.loop.set_task_factory(factory)

    def _task_done(self, _task) -> None:
        self.task_events += 1

    def close(self) -> None:
        self.executor.shutdown(wait=False, cancel_futures=True)
        self.loop.close()


def _mk_config(
    addr: Address, seeds, region: str = "", bridge_unsafe: bool = False
) -> Config:
    cfg = Config()
    cfg.addr = addr
    cfg.seed_addrs = list(seeds)
    cfg.heartbeat_time = 999.0  # never started: the explorer IS the heart
    cfg.region = region
    # bridge_unsafe arms the DELIBERATELY broken demotion rule — the
    # v10 status quo: a threshold no schedule can reach, so a dead
    # bridge stays elected forever. The bridge_demotion invariant
    # (checked against BRIDGE_DEMOTE_MODEL regardless) must then yield
    # a minimized counterexample.
    cfg.bridge_demote_ticks = (1 << 30) if bridge_unsafe else (
        BRIDGE_DEMOTE_MODEL
    )
    # provenance tracing (schema v11) stays off under the explorer: a
    # 1-in-N sampling counter in broadcast_deltas would otherwise make
    # frame bytes depend on global write ordering, multiplying the
    # explored state space without adding any modeled behavior
    cfg.trace_sample = 0
    cfg.log = Log.create_none()
    return cfg


class World:
    def __init__(
        self,
        config_name: str,
        budgets: dict | None = None,
        runtime: Runtime | None = None,
        escrow_unsafe: bool = False,
        session_unsafe: bool = False,
        bridge_unsafe: bool = False,
    ):
        if config_name not in CONFIG_NAMES:
            raise ValueError(f"unknown config {config_name!r}")
        self.config_name = config_name
        self.budgets = dict(DEFAULT_BUDGETS)
        if budgets:
            self.budgets.update(budgets)
        # escrow_unsafe arms ModelDatabase's deliberately broken
        # transfer rule (no rights check, full-bound amount): the
        # exploration MUST then find a schedule violating the bcount
        # invariant — the counterexample demonstration in test_model.py
        self.escrow_unsafe = escrow_unsafe
        # session_unsafe arms the broken session-watermark rule
        # (sessions.SessionIndex unsafe mode): the exploration MUST
        # then find a token-satisfied read observing a missing write —
        # the session_ryw counterexample demonstration
        self.session_unsafe = session_unsafe
        # bridge_unsafe arms the broken bridge-demotion rule (an
        # unreachable threshold — the pre-failover v10 behavior): the
        # bridge_demotion invariant must then yield a minimized
        # stale-bridge counterexample (PR 15)
        self.bridge_unsafe = bridge_unsafe
        self._owns_runtime = runtime is None
        self._runtime = runtime or Runtime()
        self.loop = self._runtime.loop
        self._executor = self._runtime.executor
        self.clock = VirtualClock()
        self.net = Network()
        self.instances: dict[str, Instance] = {}
        self.dbs: dict[str, ModelDatabase] = {}
        self._group_builders: dict[str, callable] = {}
        self.used = {
            "dups": 0, "kills": 0, "crashes": 0, "partitions": 0,
            "bxfers": 0, "bkills": 0,
        }
        # groups taken down by bkill and not yet rebooted: no ticks, no
        # writes, no deliveries land there; quiesce reboots them first
        self.down_groups: set[str] = set()
        self._down_journals: dict[str, list] = {}
        self.writes_left: dict[str, int] = {}
        self.bdecs_left: dict[str, int] = {}
        self.mints_left: dict[str, int] = {}
        # minted session tokens: (group, vector, own-column floor,
        # minting boot) — the read-your-writes invariant checks every
        # one at every state; the quiescence LIVENESS law additionally
        # requires universal domination, but only for tokens whose
        # minting group never crashed afterward (a crash can destroy
        # the only copy of the sequenced frames a token references —
        # the data heals via anti-entropy, the token honestly stays
        # STALE forever; docs/sessions.md documents the contract)
        self.tokens: list[tuple[str, dict, dict, int]] = []
        self.boot_count: dict[str, int] = {}
        self.group_rids: dict[str, int] = {}
        # invariant shadows: per-db lattice floor, per-(instance, addr)
        # last observed dial-backoff state
        self._floor: dict[str, dict] = {}
        self._backoff_seen: dict[tuple[str, str], tuple[int, int]] = {}
        self._build()
        # seed divergence: every group starts with one local write on
        # the shared key, so convergence is never vacuous
        for group in sorted(self.dbs):
            self.dbs[group].local_write()
        self._run(lambda: None)

    def close(self) -> None:
        def down():
            for inst in self.instances.values():
                if inst.alive:
                    inst.cluster.dispose()
            for conn in self.net.conns.values():
                conn.kill()  # EOF every parked read task

        try:
            self._run(down)
        finally:
            # reap anything still parked on a model future, so a shared
            # runtime starts the next World with a clean task table
            pending = asyncio.all_tasks(self.loop)
            for task in pending:
                task.cancel()
            if pending:
                try:
                    self.loop.run_until_complete(
                        asyncio.gather(*pending, return_exceptions=True)
                    )
                # jlint: broad-ok — best-effort reap of cancelled tasks
                # at teardown; gather(return_exceptions=True) only
                # raises loop-state errors, and a failed reap must not
                # mask the exploration's own result
                except Exception:
                    pass
            self._executor.futures.clear()
            if self._owns_runtime:
                self._runtime.close()

    # ---- construction ------------------------------------------------------

    def _spawn(self, key, group, addr, seeds, db, drive_flush=True,
               register_system=True, region="") -> Instance:
        inst = Instance(key, group, addr)
        inst.database = db
        inst.cluster = Cluster(
            _mk_config(addr, seeds, region, self.bridge_unsafe),
            db,
            drive_flush=drive_flush,
            register_system=register_system,
            clock=self.clock,
            connect=self.net.connect_fn(inst),
        )
        self.instances[key] = inst
        self.net.register(str(addr), inst)
        return inst

    def _build(self) -> None:
        if self.config_name == "nodes2":
            addrs = {
                "A": Address("10.0.0.1", "7001", "A"),
                "B": Address("10.0.0.2", "7001", "B"),
            }
            for i, name in enumerate(sorted(addrs)):
                self._node_group(name, addrs[name], [
                    a for n, a in sorted(addrs.items()) if n != name
                ], rid=i + 1)
        elif self.config_name == "nodes3":
            addrs = {
                "foo": Address("10.0.0.1", "7001", "foo"),
                "bar": Address("10.0.0.2", "7001", "bar"),
                "baz": Address("10.0.0.3", "7001", "baz"),
            }
            # bar/baz know only the seed: mesh discovery through gossip
            # is part of the explored state space (the reference test's
            # topology)
            self._node_group("foo", addrs["foo"], [], rid=1)
            self._node_group("bar", addrs["bar"], [addrs["foo"]], rid=2)
            self._node_group("baz", addrs["baz"], [addrs["foo"]], rid=3)
        elif self.config_name == "lanes2":
            # external node E + a 2-lane node N (bus + bridge)
            e_addr = Address("10.0.0.9", "7001", "E")
            n_addr = Address("10.0.0.1", "7001", "N")
            bus0 = Address("127.0.0.1", "7101", "N#lane0")
            bus1 = Address("127.0.0.1", "7102", "N#lane1")
            self._node_group("E", e_addr, [n_addr], rid=9)
            self._lane_group("L0", 0, n_addr, bus0, [bus1], e_addr, rid=1)
            self._lane_group("L1", 1, n_addr, bus1, [bus0], None, rid=2)
        else:  # regions3: two regions, one deterministic bridge each.
            # foo+bar form region ra's intra mesh (foo, the smaller
            # address, is its bridge); baz alone is region rb (its own
            # bridge). The explored topology is therefore foo<->bar and
            # the foo<->baz WAN link, with bar<->baz REACHABLE ONLY
            # through foo's origin-preserving relays — exactly the path
            # a session token minted on bar must survive to verify on
            # baz (and the path the region-prune policy must carve out
            # of the bootstrap full mesh without partitioning anyone).
            addrs = {
                "foo": Address("10.0.0.1", "7001", "foo"),
                "bar": Address("10.0.0.2", "7001", "bar"),
                "baz": Address("10.0.0.3", "7001", "baz"),
            }
            self._node_group("foo", addrs["foo"], [], rid=1, region="ra")
            self._node_group(
                "bar", addrs["bar"], [addrs["foo"]], rid=2, region="ra"
            )
            self._node_group(
                "baz", addrs["baz"], [addrs["foo"]], rid=3, region="rb"
            )

    def _node_group(self, name, addr, seeds, rid, region: str = "") -> None:
        def build(journal=None):
            db = ModelDatabase(name, rid, journal,
                               escrow_unsafe=self.escrow_unsafe,
                               session_unsafe=self.session_unsafe)
            self.dbs[name] = db
            self._spawn(name, name, addr, seeds, db, region=region)

        self._group_builders[name] = build
        self.writes_left[name] = self.budgets["writes"]
        self.bdecs_left[name] = self.budgets["bdecs"]
        self.mints_left[name] = self.budgets["mints"]
        self.group_rids[name] = rid
        build()

    def _lane_group(self, group, lane_id, n_addr, bus_addr, bus_seeds,
                    e_addr, rid) -> None:
        def build(journal=None):
            db = ModelDatabase(group, rid, journal,
                               escrow_unsafe=self.escrow_unsafe,
                               session_unsafe=self.session_unsafe)
            self.dbs[group] = db
            # main.py's exact wiring: every lane runs a bus instance
            # (lane 0's does not own the SYSTEM metrics section); lane 0
            # additionally runs the external cluster with
            # drive_flush=False and bridges the meshes
            bus = self._spawn(
                f"{group}.bus", group, bus_addr, bus_seeds, db,
                register_system=(lane_id != 0),
            )
            if lane_id == 0:
                ext = self._spawn(
                    f"{group}.ext", group, n_addr, [e_addr], db,
                    drive_flush=False,
                )
                wire_bridge(bus.cluster, ext.cluster)

        self._group_builders[group] = build
        self.writes_left[group] = self.budgets["writes"]
        self.bdecs_left[group] = self.budgets["bdecs"]
        self.mints_left[group] = self.budgets["mints"]
        self.group_rids[group] = rid
        build()

    # ---- event-loop stepping ----------------------------------------------

    def _run(self, fn) -> None:
        async def step():
            res = fn()
            if asyncio.iscoroutine(res):
                await res
            await self._settle()

        self.loop.run_until_complete(step())

    async def _settle(self) -> None:
        """Run the loop until every task is parked on a model-network
        future (or done) and no executor work is in flight. The
        fingerprint is (net progress, live task count); 8 stable
        spin rounds covers any pure-compute continuation chain."""
        stable, last = 0, None
        for _ in range(2000):
            await asyncio.sleep(0)
            pending = [f for f in self._executor.futures if not f.done()]
            if pending:
                await asyncio.wrap_future(pending[0])
                stable, last = 0, None
                continue
            self._executor.futures.clear()
            fp = (self.net.progress, self._runtime.task_events)
            if fp == last:
                stable += 1
                if stable >= 3:
                    return
            else:
                stable, last = 0, fp
        raise Violation("settle", "event loop failed to quiesce")

    # ---- actions -----------------------------------------------------------

    def _groups(self) -> list[str]:
        return sorted(self._group_builders)

    def enabled_actions(self) -> list[tuple]:
        acts: list[tuple] = []
        for cid in sorted(self.net.conns):
            conn = self.net.conns[cid]
            for direction in ("fwd", "rev"):
                link = conn.link(direction)
                recv = conn.target if direction == "fwd" else conn.dialer
                inst = self.instances.get(recv)
                if link.outbox and inst is not None and inst.alive:
                    acts.append(("deliver", cid, direction))
                    if self.used["dups"] < self.budgets["dups"]:
                        acts.append(("dup", cid, direction))
            if not conn.closed and self.used["kills"] < self.budgets["kills"]:
                acts.append(("kill", cid))
        for key in sorted(self.instances):
            if self.instances[key].alive:
                acts.append(("tick", key))
        for group in self._groups():
            if self.writes_left.get(group, 0) > 0 and self._group_alive(group):
                acts.append(("write", group))
            if self.bdecs_left.get(group, 0) > 0 and self._group_alive(group):
                acts.append(("bdec", group))
            if self.mints_left.get(group, 0) > 0 and self._group_alive(group):
                acts.append(("mint", group))
            if (
                self.used["crashes"] < self.budgets["crashes"]
                and self._group_alive(group)
            ):
                acts.append(("crash", group))
            # bridge-kill/reboot axis (PR 15, regions3): unlike crash's
            # immediate reboot, bkill leaves the group DOWN so the
            # demotion/succession window is itself explorable
            if self.config_name == "regions3":
                if (
                    self.used["bkills"] < self.budgets["bkills"]
                    and self._group_alive(group)
                ):
                    acts.append(("bkill", group))
                if group in self.down_groups:
                    acts.append(("breboot", group))
        # escrow transfers OUT of the seed-escrow group (the only group
        # holding dec-rights before any transfer): the interplay the
        # bcount invariant must survive — a transfer racing the sender's
        # own decrements, delivered or lost against each receiver
        if self.used["bxfers"] < self.budgets["bxfers"]:
            for gfrom in self._groups():
                if self.group_rids.get(gfrom) != BCOUNT_SEED_RID:
                    continue
                if not self._group_alive(gfrom):
                    continue
                for gto in self._groups():
                    if gto != gfrom and self._group_alive(gto):
                        acts.append(("bxfer", gfrom, gto))
        if self.config_name != "lanes2":
            groups = self._groups()
            for i, a in enumerate(groups):
                for b in groups[i + 1:]:
                    pair = frozenset((a, b))
                    if pair in self.net.partitions:
                        acts.append(("heal", a, b))
                    elif self.used["partitions"] < self.budgets["partitions"]:
                        acts.append(("part", a, b))
        return acts

    def _group_alive(self, group: str) -> bool:
        if group in self.down_groups:
            return False
        return all(
            i.alive for i in self.instances.values() if i.group == group
        )

    def action_enabled(self, action: tuple) -> bool:
        """Targeted membership test, equivalent to `action in
        enabled_actions()` without rebuilding the whole frontier —
        apply() runs this once per REPLAYED action, which is the
        exploration hot path."""
        kind = action[0]
        if kind == "tick":
            inst = self.instances.get(action[1])
            return inst is not None and inst.alive
        if kind in ("deliver", "dup"):
            if kind == "dup" and self.used["dups"] >= self.budgets["dups"]:
                return False
            conn = self.net.conns.get(action[1])
            if conn is None or action[2] not in ("fwd", "rev"):
                return False
            recv = conn.target if action[2] == "fwd" else conn.dialer
            inst = self.instances.get(recv)
            return bool(
                conn.link(action[2]).outbox
                and inst is not None
                and inst.alive
            )
        if kind == "kill":
            conn = self.net.conns.get(action[1])
            return (
                conn is not None
                and not conn.closed
                and self.used["kills"] < self.budgets["kills"]
            )
        if kind == "write":
            return (
                self.writes_left.get(action[1], 0) > 0
                and action[1] in self._group_builders
                and self._group_alive(action[1])
            )
        if kind == "bdec":
            return (
                self.bdecs_left.get(action[1], 0) > 0
                and action[1] in self._group_builders
                and self._group_alive(action[1])
            )
        if kind == "mint":
            return (
                self.mints_left.get(action[1], 0) > 0
                and action[1] in self._group_builders
                and self._group_alive(action[1])
            )
        if kind == "bxfer":
            return (
                self.used["bxfers"] < self.budgets["bxfers"]
                and self.group_rids.get(action[1]) == BCOUNT_SEED_RID
                and action[2] in self._group_builders
                and action[1] != action[2]
                and self._group_alive(action[1])
                and self._group_alive(action[2])
            )
        if kind == "crash":
            return (
                action[1] in self._group_builders
                and self.used["crashes"] < self.budgets["crashes"]
                and self._group_alive(action[1])
            )
        if kind == "bkill":
            return (
                self.config_name == "regions3"
                and action[1] in self._group_builders
                and self.used["bkills"] < self.budgets["bkills"]
                and self._group_alive(action[1])
            )
        if kind == "breboot":
            return (
                self.config_name == "regions3"
                and action[1] in self.down_groups
            )
        if kind == "part":
            return (
                self.config_name != "lanes2"
                and action[1] in self._group_builders
                and action[2] in self._group_builders
                and action[1] != action[2]
                and frozenset((action[1], action[2]))
                not in self.net.partitions
                and self.used["partitions"] < self.budgets["partitions"]
            )
        if kind == "heal":
            return frozenset((action[1], action[2])) in self.net.partitions
        return False

    def apply(self, action: tuple) -> bool:
        """Fire one action then settle; False if it is not currently
        enabled (replay after a code change skips, never crashes)."""
        action = tuple(action)
        if not self.action_enabled(action):
            return False
        kind = action[0]
        if kind == "tick":
            inst = self.instances[action[1]]
            self.clock.advance(TICK_MS)
            self._run(inst.cluster._heartbeat)
        elif kind == "deliver":
            link = self.net.conns[action[1]].link(action[2])
            self._run(link.deliver_one)
        elif kind == "dup":
            self.used["dups"] += 1
            link = self.net.conns[action[1]].link(action[2])
            self._run(link.duplicate_one)
        elif kind == "kill":
            self.used["kills"] += 1
            self._run(self.net.conns[action[1]].kill)
        elif kind == "write":
            self.writes_left[action[1]] -= 1
            self._run(self.dbs[action[1]].local_write)
        elif kind == "bdec":
            self.bdecs_left[action[1]] -= 1
            self._run(self.dbs[action[1]].local_bdec)
        elif kind == "mint":
            self.mints_left[action[1]] -= 1
            self._mint(action[1])
        elif kind == "bxfer":
            self.used["bxfers"] += 1
            to_rid = self.group_rids[action[2]]
            self._run(
                lambda: self.dbs[action[1]].local_bxfer(to_rid)
            )
        elif kind == "crash":
            self.used["crashes"] += 1
            self._crash_reboot(action[1])
        elif kind == "bkill":
            self.used["bkills"] += 1
            self._kill_group(action[1])
        elif kind == "breboot":
            self._reboot_group(action[1])
        elif kind == "part":
            self.used["partitions"] += 1
            pair = frozenset((action[1], action[2]))
            self.net.partitions.add(pair)
            self._run(lambda: self.net.kill_between(action[1], action[2]))
        elif kind == "heal":
            self.net.partitions.discard(frozenset((action[1], action[2])))
            self._run(lambda: None)
        else:
            raise ValueError(f"unknown action {action!r}")
        self.net.gc_conns()
        return True

    def _mint(self, group: str) -> None:
        """SESSION TOKEN at ``group``: force its pending local deltas
        through the driving cluster's flush path (the product's
        Database._mint_token barrier), snapshot the vector, and record
        the group's OWN counter columns as the token's floor — exactly
        the writes the token's self entry covers. The session_ryw
        invariant then holds the floor against every replica whose
        vector ever dominates the token."""
        inst = self.instances.get(group) or self.instances.get(
            f"{group}.bus"
        )
        self._run(inst.cluster.flush_now)
        db = self.dbs[group]
        vec = dict(db.sessions.vector())
        rid = self.group_rids[group]
        # floor = own columns SHIPPED under this incarnation: what the
        # vector's self entry provably covers. (Journal-replayed state
        # a reboot never re-shipped is NOT claimable by a fresh token —
        # the explorer found exactly that over-claim in an earlier cut.)
        floor = {
            (key.hex(), rid): n for key, n in db.own_shipped.items()
        }
        self.tokens.append(
            (group, vec, floor, self.boot_count.get(group, 0))
        )

    def _crash_reboot(self, group: str) -> None:
        self._kill_group(group)
        self._reboot_group(group)

    def _kill_group(self, group: str) -> None:
        """Take a group down and LEAVE it down (the bkill half): its
        journal is snapshotted for the eventual reboot, its instances
        dispose, its conns die abortively. The explorable window
        between this and the matching breboot is where bridge
        demotion, deterministic succession and the dual-bridge overlap
        live."""
        self._down_journals[group] = list(self.dbs[group].journal)
        self.down_groups.add(group)

        def down():
            for key in [
                k for k, i in self.instances.items() if i.group == group
            ]:
                inst = self.instances.pop(key)
                inst.alive = False
                inst.cluster.dispose()
            self.net.kill_of_group(group)

        self._run(down)
        self.net.gc_conns()

    def _reboot_group(self, group: str) -> None:
        # a reboot is a new incarnation: advance the virtual clock so
        # the rebuilt Cluster mints a fresh boot epoch (production wall
        # time guarantees this; the model must too, or the new seq
        # stream would alias the old one in every peer's session vector)
        self.clock.advance(TICK_MS)
        self.boot_count[group] = self.boot_count.get(group, 0) + 1
        journal = self._down_journals.pop(group)
        self.down_groups.discard(group)
        # reboot from "disk": the journaled local writes survive,
        # converged remote state heals back over the rejoin sync
        self._group_builders[group](journal)
        # floor resets with the reboot: losing REMOTE state at a crash
        # is the documented durability model, not a join regression
        self._floor.pop(group, None)
        for k in [k for k in self._backoff_seen if k[0].startswith(group)]:
            del self._backoff_seen[k]
        self._run(lambda: None)

    # ---- invariants --------------------------------------------------------

    def check_invariants(self) -> None:
        # lattice monotonicity: no (key, replica) cell ever regresses
        for group, db in self.dbs.items():
            cells = db.cells()
            floor = self._floor.get(group, {})
            for cell, v in floor.items():
                if cells.get(cell, 0) < v:
                    raise Violation(
                        "monotonicity",
                        f"{group}: cell {cell} regressed {v} -> "
                        f"{cells.get(cell, 0)}",
                    )
            self._floor[group] = cells
            # BCOUNT escrow safety (schema v9): 0 <= value <= bound on
            # EVERY replica's local view in EVERY reachable state — the
            # invariant the escrow construction exists to enforce
            # without coordination (ops/bcount.py). A deliberately
            # broken escrow rule (World(escrow_unsafe=True)) must
            # surface here as a minimized counterexample schedule.
            for key, bc in db.state_b.items():
                value, bound = bc.value(), bc.bound()
                if value < 0:
                    raise Violation(
                        "bcount_negative",
                        f"{group}: {key!r} value {value} < 0 "
                        f"(decs outran the escrow that funded them)",
                    )
                if value > bound:
                    raise Violation(
                        "bcount_bound",
                        f"{group}: {key!r} value {value} > bound {bound}",
                    )
        # session guarantee (schema v10): a token-satisfied read never
        # observes a regression — any replica whose applied vector
        # dominates a minted token must show the token's floor (the
        # minting group's own counter columns at mint time). This is
        # THE read-your-writes invariant, checked at every state; the
        # deliberately broken watermark rule (session_unsafe) must
        # surface here as a minimized counterexample schedule.
        for g0, vec, floor, _boot in self.tokens:
            for group, db in self.dbs.items():
                if not self._group_alive(group):
                    continue
                svec = db.sessions.vector()
                if not all(svec.get(r, 0) >= s for r, s in vec.items()):
                    continue  # not dominated: STALE territory, no claim
                for (key_hex, rid), v in floor.items():
                    got = db.state.get(bytes.fromhex(key_hex), {}).get(
                        rid, 0
                    )
                    if got < v:
                        raise Violation(
                            "session_ryw",
                            f"{group}: dominates {g0}'s token but cell "
                            f"({key_hex}, {rid}) shows {got} < floor {v}",
                        )
        for key, inst in self.instances.items():
            if not inst.alive:
                continue
            c = inst.cluster
            # bounded handover (PR 15): a node never keeps electing a
            # bridge its OWN evidence says has been silent past the
            # demotion bound while a live successor exists. Checked
            # against BRIDGE_DEMOTE_MODEL — NOT the instance's armed
            # threshold — so the deliberately broken never-demote rule
            # (bridge_unsafe) surfaces here as a minimized stale-bridge
            # counterexample while the safe rule survives the identical
            # schedule by construction.
            if c._region:
                b = c._bridge_of(c._region)
                me = str(inst.addr)
                seen = c._seen_tick.get(b) if b is not None else None
                if (
                    b is not None
                    and b != me
                    and seen is not None
                    and c._tick - seen > BRIDGE_DEMOTE_MODEL
                ):
                    def _fresh(a) -> bool:
                        if str(a) == me:
                            return True
                        t = c._seen_tick.get(str(a))
                        return (
                            t is not None
                            and c._tick - t <= BRIDGE_DEMOTE_MODEL
                        )

                    alt = any(
                        _fresh(a)
                        for a in c._known_addrs
                        if str(a) != b
                        and c._regions.get(str(a), ("", 0))[0]
                        == c._region
                    )
                    if alt:
                        raise Violation(
                            "bridge_demotion",
                            f"{key}: elected bridge {b} silent "
                            f"{c._tick - seen} ticks (bound "
                            f"{BRIDGE_DEMOTE_MODEL}) with a live "
                            "successor available",
                        )
            # held queue: bounded and FIFO by hold time
            if len(c._held) > c._held_cap:
                raise Violation(
                    "held_bound",
                    f"{key}: {len(c._held)} held > cap {c._held_cap}",
                )
            stamps = [ts for ts, _ in c._held]
            if stamps != sorted(stamps):
                raise Violation("held_fifo", f"{key}: held stamps {stamps}")
            # delta-interval sender state (schema v8): the retransmit
            # window is bounded and strictly seq-ordered, and no peer's
            # acked watermark outruns the sender's own seq counter
            if len(c._delta_log) > c._delta_log_cap:
                raise Violation(
                    "delta_log_bound",
                    f"{key}: {len(c._delta_log)} logged > cap "
                    f"{c._delta_log_cap}",
                )
            seqs = [s for s, _ in c._delta_log]
            if seqs != sorted(seqs) or len(set(seqs)) != len(seqs):
                raise Violation(
                    "delta_log_order", f"{key}: window seqs {seqs}"
                )
            if seqs and seqs[-1] > c._delta_seq:
                raise Violation(
                    "delta_log_order",
                    f"{key}: window head {seqs[-1]} > seq {c._delta_seq}",
                )
            for addr, st in c._peers.items():
                if st.acked is not None and st.acked > c._delta_seq:
                    raise Violation(
                        "ack_bound",
                        f"{key}->{addr}: acked {st.acked} > delta_seq "
                        f"{c._delta_seq}",
                    )
            # receiver interval state: the out-of-order park is bounded
            # and strictly above the contiguity cursor
            for skey, ooo in c._recv_ooo.items():
                if len(ooo) > cluster_mod.RECV_OOO_CAP:
                    raise Violation(
                        "ooo_bound", f"{key}<-{skey}: {len(ooo)} parked"
                    )
                cum = c._recv_cum.get(skey, 0)
                if ooo and min(ooo) <= cum + 1:
                    raise Violation(
                        "ooo_order",
                        f"{key}<-{skey}: parked {sorted(ooo)[:4]} at cum "
                        f"{cum} (contiguous seqs must collapse)",
                    )
            # dial backoff: bounded above by cap(+jitter), monotone
            # while failures accumulate (reset only by contact)
            for addr, st in c._peers.items():
                wait = st.next_dial_tick - c._tick
                bound = c._backoff_cap + c._backoff_cap // 2 + 1
                if st.fails > 0 and wait > bound:
                    raise Violation(
                        "backoff_bound",
                        f"{key}->{addr}: wait {wait} ticks > bound {bound}",
                    )
                seen = self._backoff_seen.get((key, str(addr)))
                if (
                    seen is not None
                    and st.fails > seen[0]
                    and st.next_dial_tick < seen[1]
                ):
                    raise Violation(
                        "backoff_monotone",
                        f"{key}->{addr}: fails {seen[0]}->{st.fails} but "
                        f"next_dial {seen[1]}->{st.next_dial_tick}",
                    )
                self._backoff_seen[(key, str(addr))] = (
                    st.fails, st.next_dial_tick,
                )

    # ---- quiescence + global laws -----------------------------------------

    def _deliver_all(self, cap: int = 200) -> None:
        for _ in range(cap):
            moved = 0

            def burst():
                nonlocal moved
                for cid in sorted(self.net.conns):
                    conn = self.net.conns[cid]
                    for direction in ("fwd", "rev"):
                        link = conn.link(direction)
                        recv = (
                            conn.target if direction == "fwd"
                            else conn.dialer
                        )
                        inst = self.instances.get(recv)
                        while (
                            link.outbox and inst is not None and inst.alive
                        ):
                            link.deliver_one()
                            moved += 1

            # quiescence needs no per-frame interleaving control: one
            # settle per burst, not per frame
            self._run(burst)
            self.net.gc_conns()
            if not moved:
                return
        raise Violation("quiesce", "deliveries never drained")

    def _digests(self) -> dict[str, str]:
        return {g: db.digest().hex() for g, db in sorted(self.dbs.items())}

    def quiesce(self) -> None:
        """Heal everything, run to a fixpoint, assert the global laws:
        digest match on every replica, no in-flight or held frames, no
        stranded rtt stamps."""
        self.net.partitions.clear()
        # groups still down from a bkill reboot first: quiescence is
        # about the HEALED system, and a down group can neither
        # converge nor serve its half of any invariant
        for group in sorted(self.down_groups):
            self._reboot_group(group)
        period = cluster_mod.SYNC_PERIOD_TICKS
        stable = 0
        for _ in range(40 * period):
            self._deliver_all()
            if len(set(self._digests().values())) == 1:
                stable += 1
                # a full extra sync period after digests agree lets the
                # in-flight sync conversations and pong traffic finish
                if stable > period + 2:
                    break
            else:
                stable = 0
            for key in sorted(self.instances):
                if self.instances[key].alive:
                    self.clock.advance(TICK_MS)
                    self._run(self.instances[key].cluster._heartbeat)
        self._deliver_all()
        digests = self._digests()
        if len(set(digests.values())) != 1:
            raise Violation("convergence", f"digest mismatch: {digests}")
        for key, inst in sorted(self.instances.items()):
            if not inst.alive:
                continue
            c = inst.cluster
            if c._held:
                raise Violation(
                    "held_drained", f"{key}: {len(c._held)} frames held "
                    "after quiescence",
                )
            for addr, conn in sorted(
                c._actives.items(), key=lambda kv: str(kv[0])
            ):
                if conn.established and conn.pong_sent:
                    raise Violation(
                        "rtt_stamps",
                        f"{key}->{addr}: {len(conn.pong_sent)} stranded "
                        "rtt stamps after quiescence",
                    )
                if conn.range_pending:
                    raise Violation(
                        "range_walk_done",
                        f"{key}->{addr}: range walk stalled with "
                        f"{sorted(conn.range_pending)} pending",
                    )
            # the v8 repair machinery fully drains at quiescence: no
            # parked out-of-order seqs, no queued range serves, and no
            # peer still owed a range repair (interval-dirty)
            if any(c._recv_ooo.values()):
                raise Violation(
                    "ooo_drained",
                    f"{key}: out-of-order seqs parked after quiescence",
                )
            if c._range_queue:
                raise Violation(
                    "range_queue_drained",
                    f"{key}: {len(c._range_queue)} range serves queued",
                )
            if c._relay_queue:
                raise Violation(
                    "relay_queue_drained",
                    f"{key}: {len(c._relay_queue)} repair relays queued "
                    "after quiescence",
                )
            for addr, st in sorted(
                c._peers.items(), key=lambda kv: str(kv[0])
            ):
                if st.interval_dirty and str(addr) in {
                    str(i.addr) for i in self.instances.values() if i.alive
                }:
                    raise Violation(
                        "dirty_cleared",
                        f"{key}->{addr}: still interval-dirty after "
                        "quiescence (range repair never completed)",
                    )
        for cid, conn in sorted(self.net.conns.items()):
            for direction in ("fwd", "rev"):
                link = conn.link(direction)
                if link.outbox or link.inbox:
                    raise Violation(
                        "in_flight", f"{cid}/{direction} still carries "
                        "bytes after quiescence",
                    )
        self._quiesce_sessions()

    def _quiesce_sessions(self) -> None:
        """Session liveness at quiescence: once everything healed and
        every digest matches, every minted token must become dominated
        on every alive replica — live contiguity covers the direct
        paths, digest-match adoption covers reboots and region hops.
        Adoption can need a couple more sync periods after the digests
        first agree (it rides the periodic MsgSyncRequest exchange, and
        a vector entry may have to hop bridge-wise), so tick a bounded
        extra window before asserting."""
        if not self.tokens:
            return
        period = cluster_mod.SYNC_PERIOD_TICKS

        def all_dominated() -> bool:
            for g0, vec, _floor, boot in self.tokens:
                if self.boot_count.get(g0, 0) != boot:
                    # the minting group crashed after the mint: the
                    # token's frames may be unrecoverable — it honestly
                    # stays STALE (safety still checked every state)
                    continue
                for group, db in self.dbs.items():
                    if not self._group_alive(group):
                        continue
                    svec = db.sessions.vector()
                    if not all(
                        svec.get(r, 0) >= s for r, s in vec.items()
                    ):
                        return False
            return True

        for _ in range(8 * period):
            if all_dominated():
                return
            for key in sorted(self.instances):
                if self.instances[key].alive:
                    self.clock.advance(TICK_MS)
                    self._run(self.instances[key].cluster._heartbeat)
            self._deliver_all()
        if not all_dominated():
            raise Violation(
                "session_liveness",
                "a minted token is still not dominated everywhere "
                "after quiescence + adoption window",
            )

    # ---- state hashing -----------------------------------------------------

    @staticmethod
    def _sha(data: bytes) -> str:
        return hashlib.sha256(data).hexdigest()[:16]

    def _rel(self, tick: int, t) -> int | None:
        if t is None:
            return None
        period = cluster_mod.SYNC_PERIOD_TICKS
        return min(tick - t, 8 * period)

    def canonical(self):
        period = cluster_mod.SYNC_PERIOD_TICKS
        mod = cluster_mod.ANNOUNCE_EVERY * period
        # rank-normalise every wall-ms the state carries: absolute
        # virtual-clock values would make every state unique
        times = set()
        for inst in self.instances.values():
            if inst.alive:
                c = inst.cluster
                times.update(ts for ts, _ in c._held)
                if c._defer_since_ms is not None:
                    times.add(c._defer_since_ms)
        rank = {t: i for i, t in enumerate(sorted(times))}
        dbs = {
            g: {
                "digest": db.digest().hex()[:16],
                "pending": [
                    (k.hex(), sorted(d.items())) for k, d in db.pending
                ],
                "pending_t": [
                    (k.hex(), self._sha(repr(t.canon()).encode()))
                    for k, t in db.pending_t
                ],
                "pending_m": [
                    (k.hex(), self._sha(repr(u).encode()))
                    for k, u in db.pending_m
                ],
                "pending_b": [
                    (k.hex(), self._sha(repr(w).encode()))
                    for k, w in db.pending_b
                ],
                "refused": db.refused_decs,
                "journal_len": len(db.journal),
                # the applied-interval vector + parked seqs (v10): two
                # states differing only here answer a SESSION READ
                # differently, so they must not dedup-merge — and the
                # shipped-floor feeds future mints' claims
                "svec": db.sessions.canonical(),
                "shipped": sorted(
                    (k.hex(), n) for k, n in db.own_shipped.items()
                ),
            }
            for g, db in sorted(self.dbs.items())
        }
        insts = {}
        for key in sorted(self.instances):
            inst = self.instances[key]
            if not inst.alive:
                insts[key] = "down"
                continue
            c = inst.cluster
            tick = c._tick
            insts[key] = {
                "tick_mod": tick % mod,
                "known": [
                    sorted(str(a) for a in c._known_addrs.adds),
                    sorted(str(a) for a in c._known_addrs.removes),
                ],
                "actives": {
                    str(a): [
                        conn.established,
                        len(conn.pong_sent),
                        self._rel(tick, conn.sync_served_tick),
                        conn.sync_defer_streak,
                        self._rel(tick, conn.sync_defer_last_tick),
                        conn.last_write_dropped,
                        # idle age drives the eviction machine: without
                        # it a 6-ticks-idle conn (evicts next tick)
                        # dedup-merges with a fresh one and the
                        # eviction subtree is never explored
                        self._rel(tick, c._last_activity.get(conn)),
                        # the requester's range-walk cursor (v8)
                        sorted(
                            (n, tuple(b)) for n, b in
                            conn.range_pending.items()
                        ),
                    ]
                    for a, conn in sorted(
                        c._actives.items(), key=lambda kv: str(kv[0])
                    )
                },
                "passives": sorted(
                    [str(conn.peer_addr), conn.established,
                     len(conn.pong_sent),
                     self._rel(tick, c._last_activity.get(conn))]
                    for conn in c._passives
                ),
                "peers": {
                    str(a): [st.fails, max(st.next_dial_tick - tick, 0)]
                    for a, st in sorted(
                        c._peers.items(), key=lambda kv: str(kv[0])
                    )
                    if st.fails or st.next_dial_tick > tick
                },
                # delta-interval state (v8): the sender's seq counter +
                # retransmit window, per-peer ack watermarks and dirty
                # flags, and the receiver's per-sender cursors/parks —
                # all protocol-relevant (a state differing only here
                # behaves differently on the next reconnect)
                "interval": [
                    c._delta_seq,
                    [[seq, self._sha(data)] for seq, data in c._delta_log],
                    sorted(
                        (str(a), st.acked, st.interval_dirty, st.reset_seq)
                        for a, st in c._peers.items()
                        if st.acked is not None or st.interval_dirty
                    ),
                    sorted(c._recv_cum.items()),
                    sorted(
                        (skey, tuple(sorted(ooo)))
                        for skey, ooo in c._recv_ooo.items()
                        if ooo
                    ),
                    len(c._range_queue),
                ],
                "held": [
                    [rank[ts], self._sha(data)] for ts, data in c._held
                ],
                # region topology state (v10): the gossiped region map
                # drives dial policy and relay roles
                "regions": sorted(c._regions.items()),
                # bridge failover (PR 15): per-address liveness ages
                # (capped at the demote bound + 1 — election only asks
                # "over or under", so finer ages would defeat dedup for
                # nothing), the elected bridge, and the repair relay
                # queue. Region-less instances skip all three (the
                # state exists but drives no behavior there).
                "bridge": [
                    sorted(
                        (a, min(tick - t, c._bridge_demote + 1))
                        for a, t in c._seen_tick.items()
                    ),
                    c._bridge_seen if c._bridge_seen != () else None,
                    [len(c._relay_queue), c._relay_queue_bytes],
                ] if c._region else None,
                "stats": sorted(c._stats.items()),
                "drops": sorted(c._drop_counts.items()),
                "msg_drops": sorted(c._msg_drops.items()),
                "sync": [
                    self._rel(tick, c._sync_rx_tick),
                    sorted(
                        (str(a), self._rel(tick, t))
                        for a, t in c._sync_req_tick.items()
                    ),
                    sorted(str(a) for a in c._sync_req_inflight),
                    len(c._sync_waiters),
                    c._sync_dump_inflight,
                    c._sync_defer_streak,
                    c._sync_serve_defer_total,
                    self._rel(tick, c._sync_defer_total_tick),
                    c._local_writes_seen,
                    None if c._defer_since_ms is None
                    else rank[c._defer_since_ms],
                ],
            }
        conns = {
            cid: {
                "closed": conn.closed,
                "links": {
                    d: [
                        [self._sha(f) for f in conn.link(d).outbox],
                        self._sha(bytes(conn.link(d).inbox)),
                        conn.link(d).closed,
                    ]
                    for d in ("fwd", "rev")
                },
            }
            for cid, conn in sorted(self.net.conns.items())
        }
        return {
            "config": self.config_name,
            "dbs": dbs,
            "instances": insts,
            "conns": conns,
            "partitions": sorted(sorted(p) for p in self.net.partitions),
            "down": sorted(self.down_groups),
            "used": sorted(self.used.items()),
            "writes_left": sorted(self.writes_left.items()),
            "bdecs_left": sorted(self.bdecs_left.items()),
            "mints_left": sorted(self.mints_left.items()),
            "boots": sorted(self.boot_count.items()),
            "tokens": [
                (g, sorted(vec.items()), sorted(floor.items()), boot)
                for g, vec, floor, boot in self.tokens
            ],
        }

    def state_hash(self) -> str:
        # repr, not json.dumps: canonical() builds every dict in sorted
        # insertion order, so repr is deterministic — and measurably
        # cheaper than the json encoder at tens of thousands of states
        return hashlib.sha256(repr(self.canonical()).encode()).hexdigest()
