"""`make lint-native`: clang-tidy over native/ + NOLINT-reason policy.

Two jobs, mirroring jlint's discipline for the C++ tree:

1. Run clang-tidy with the committed curated check set (.clang-tidy,
   warnings-as-errors) over every native/*.cpp translation unit. When
   clang-tidy is not installed the step SKIPS with exit 0 and a loud
   message — the container image may not carry it, CI installs it; the
   repo's hard native gates (-Werror build, ASAN/UBSAN) run either way.
2. Enforce the suppression-reason policy regardless of clang-tidy
   availability: every inline ``NOLINT``/``NOLINTNEXTLINE`` in native/
   must name its check(s) AND carry a ``-- <reason>`` trailer, exactly
   like jlint's ``# jlint: <slug> — reason`` rule (JL002). A bare
   NOLINT is an unreviewable hole and fails here even without
   clang-tidy present.
"""

from __future__ import annotations

import os
import re
import shutil
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(ROOT, "native")

# NOLINT with a named check AND a reason: `// NOLINT(check) -- why`
_NOLINT_RE = re.compile(r"//\s*NOLINT(NEXTLINE)?(?P<rest>.*)$")
_GOOD_RE = re.compile(r"^\((?P<checks>[\w\-.,* ]+)\)\s*--\s*\S.*")


def check_nolint_reasons() -> int:
    bad = 0
    for fname in sorted(os.listdir(NATIVE)):
        if not fname.endswith((".cpp", ".h")):
            continue
        path = os.path.join(NATIVE, fname)
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f, 1):
                m = _NOLINT_RE.search(line)
                if m is None:
                    continue
                if _GOOD_RE.match(m.group("rest").strip()) is None:
                    bad += 1
                    print(
                        f"native/{fname}:{i}: NOLINT must name its "
                        "check(s) and carry a reason — "
                        "`// NOLINT(<check>) -- <why this is safe>` "
                        "(same policy as jlint inline suppressions)",
                        file=sys.stderr,
                    )
    return bad


def find_clang_tidy() -> str | None:
    cand = os.environ.get("CLANG_TIDY")
    if cand and shutil.which(cand):
        return cand
    for name in ("clang-tidy", "clang-tidy-19", "clang-tidy-18", "clang-tidy-17"):
        if shutil.which(name):
            return name
    return None


def run_clang_tidy(exe: str) -> int:
    sources = sorted(
        os.path.join(NATIVE, f)
        for f in os.listdir(NATIVE)
        if f.endswith(".cpp")
    )
    if not sources:
        print("lint-native: no native sources found", file=sys.stderr)
        return 1
    cmd = [exe, "--quiet", *sources, "--", "-std=c++17", "-x", "c++"]
    print("lint-native:", " ".join(os.path.relpath(c, ROOT) if os.sep in c else c for c in cmd))
    proc = subprocess.run(cmd, cwd=ROOT)
    return proc.returncode


def main() -> int:
    rc = 0
    bad = check_nolint_reasons()
    if bad:
        print(f"lint-native: {bad} bare NOLINT(s)", file=sys.stderr)
        rc = 1
    exe = find_clang_tidy()
    if exe is None:
        print(
            "lint-native: clang-tidy not installed — SKIPPING the "
            "static checks (CI installs it; the -Werror build and "
            "ASAN/UBSAN gates still run). NOLINT-reason policy was "
            "checked above."
        )
        return rc
    tidy_rc = run_clang_tidy(exe)
    if tidy_rc:
        print(
            "lint-native: clang-tidy found issues (warnings are errors "
            "per .clang-tidy) — fix them or suppress with "
            "`// NOLINT(<check>) -- <reason>`",
            file=sys.stderr,
        )
        rc = rc or tidy_rc
    else:
        print("lint-native: clang-tidy clean")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
