# makes scripts/ importable so `python -m scripts.jlint` (and the jlint
# self-tests) resolve the analyzer as a package from the repo root
