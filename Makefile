# jylis-tpu build/test targets (reference analog: the upstream Makefile's
# test/build/debug targets, SURVEY.md section 2.8)

PY ?= python

.PHONY: test soak bench bench-all bench-full bench-smoke native run clean \
        check-graft ci check-prose image compose-smoke smoke3 release \
        lint lint-native sanitize sanitize-threads chaos metrics-smoke \
        model-smoke loadgen-smoke

# what CI runs per commit (.github/workflows/ci.yml + .circleci/config.yml):
# hermetic on any host. `test` includes the journal suite
# (tests/test_journal.py — append/replay, corruption classes, rotation, and
# a real SIGKILL/restart boot); `lint` is the repo-native static analyzer
# (scripts/jlint — async/thread safety, JAX trace discipline, native/Python
# RESP surface parity, failpoint manifest parity); `sanitize` rebuilds the
# native engine under ASAN+UBSAN with -Werror and re-runs the jax-free
# native test subset; `sanitize-threads` rebuilds it under TSAN and runs
# the multi-threaded engine drive; `chaos` is the tiny fault-injection
# drill smoke.
ci: native lint lint-native test chaos model-smoke check-graft check-prose \
    bench-smoke metrics-smoke loadgen-smoke sanitize sanitize-threads

# the eleven jlint passes + the hygiene rules (broad-except, suppression
# reasons/staleness), against the committed baseline
# (scripts/jlint/baseline.json — every entry justified in-line, stale
# entries fail). The manifest checks (RESP parity, failpoints, metrics,
# lane shared-state, codec symmetry, lattice discipline, protocol
# atlas, cross-language RESP semantics) re-extract
# their surfaces on every run and fail on uncommitted drift; regenerate
# with `$(PY) -m scripts.jlint --write-manifest` (then `--write-corpus`
# if the codec or semantics manifest changed) and commit the diff.
# `--budget` fails
# the run past the recorded wall-time bound (scripts/jlint/budget.json);
# lint_findings.json is the machine-readable CI artifact.
lint:
	$(PY) -m scripts.jlint --budget --out lint_findings.json

# clang-tidy over native/ with the committed curated .clang-tidy
# (warnings-as-errors) + the NOLINT-must-carry-a-reason policy; skips
# the tidy step (exit 0, loud message) when clang-tidy is not installed
# — the -Werror build and `make sanitize` still gate the C++ either way
lint-native:
	$(PY) scripts/lint_native.py

# ASAN+UBSAN build of the native engine (-Werror, no recovery) + the
# jax-free native test subset under the sanitizer runtime. jax stays
# un-imported (JYLIS_SANITIZE gates tests/conftest.py): jaxlib's pybind11
# C++ exceptions abort under the preloaded ASAN interceptor.
sanitize:
	g++ -O1 -g -std=c++17 -shared -fPIC -fsanitize=address,undefined \
	  -fno-sanitize-recover=all -Wall -Wextra -Werror \
	  -o native/libjylis_native_san.so native/*.cpp
	JYLIS_SANITIZE=1 JYLIS_NATIVE_SO=$(abspath native/libjylis_native_san.so) \
	  LD_PRELOAD=$$(g++ -print-file-name=libasan.so) \
	  ASAN_OPTIONS=detect_leaks=0 \
	  UBSAN_OPTIONS=print_stacktrace=1,halt_on_error=1 \
	  $(PY) -m pytest tests/test_native_resp.py tests/test_native_drive.py \
	  -q -p no:cacheprovider

# TSAN build of the native engine + the multi-threaded ServeEngine
# drive (tests/test_native_tsan.py): per-thread engine isolation
# (concurrent full-surface bursts — ctypes drops the GIL, so the C++
# genuinely runs in parallel) and the external-mutex discipline for a
# shared engine (memo install/invalidate, interner compaction under
# ingest). Skips loudly (exit 0) when the toolchain has no libtsan —
# same policy as clang-tidy in lint-native; the same module still runs
# GIL-only in tier-1 either way. jax stays un-imported (JYLIS_SANITIZE),
# as in `sanitize`.
sanitize-threads:
	@tsan=$$(g++ -print-file-name=libtsan.so); \
	if [ "$$tsan" = "libtsan.so" ] || [ ! -e "$$tsan" ]; then \
	  echo "sanitize-threads: libtsan not found on this toolchain — TSAN step skipped"; \
	  echo "(tests/test_native_tsan.py still runs un-instrumented in tier-1)"; \
	  exit 0; \
	fi; \
	set -e; \
	g++ -O1 -g -std=c++17 -shared -fPIC -fsanitize=thread \
	  -Wall -Wextra -Werror \
	  -o native/libjylis_native_tsan.so native/*.cpp; \
	JYLIS_SANITIZE=1 JYLIS_NATIVE_SO=$(abspath native/libjylis_native_tsan.so) \
	  LD_PRELOAD=$$tsan \
	  TSAN_OPTIONS=halt_on_error=1,second_deadlock_stack=1 \
	  $(PY) -m pytest tests/test_native_tsan.py -q -p no:cacheprovider

# every README headline number must match the committed BENCH_full.json
check-prose:
	$(PY) scripts/check_prose.py

# tiny-iteration pass over the serving-bench harness (the RESP reply
# counter, fallback accounting, demotion path, latency loop) so the
# plumbing behind the recorded numbers can't rot between re-records;
# pinned to CPU — it checks the harness, not the hardware
bench-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py --smoke

# boot a real node with --metrics-port, scrape it, validate the
# Prometheus exposition grammar + presence of every histogram/gauge in
# scripts/jlint/metrics_manifest.json; then boot a --lanes 4 node and
# validate the supervisor's AGGREGATED scrape (per-lane labels +
# lane-less counter sums) — neither surface can rot
metrics-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/metrics_smoke.py

# tiny in-process pass over the open-loop load harness (scripts/loadgen.py
# — the worker protocol, Zipfian key draw, phase ladder, reservoir
# percentiles, BUSY/shed accounting against a real armed node) so the
# plumbing behind the overload-shed numbers can't rot between re-records.
# The per-phase JSON artifact (throughput, refusals, full log2 latency
# histogram per class) lands in loadgen_phases.json; both CI configs
# upload it so load-shape drift is diffable like lint_findings.json
loadgen-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/loadgen.py --smoke \
		--out loadgen_phases.json

test:
	$(PY) -m pytest tests/ -x -q

# tiny fault-injection drill smoke: a curated subset of the drill
# matrix — dial backoff/reset/timeout drills, an FFI fault served via
# demotion, the CLUSTER metrics surface, and the LANE-CRASH cell
# (SIGKILL one lane of a spawned --lanes 2 node mid-traffic; surviving
# lanes serve throughout, the respawn replays its journal segment,
# per-lane digests re-match) — per commit via `make ci`. The FULL
# {error,sleep,corrupt,drop,crash} x {every registered failpoint}
# matrix plus the 3-node lane drills run nightly behind `-m soak`.
chaos:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_drill_matrix.py -m chaos -q

# jmodel: bounded explicit-state exploration of the cluster + lane-bus
# protocol (scripts/jmodel). Drives the REAL Cluster handler code over
# an in-memory deterministic network (virtual clock + pipe transport
# through cluster.py's injectable clock/connect seams), enumerating
# delivery schedules — reorder across conns, drop (conn kill),
# duplicate, partition, crash-reboot-from-journal — over the 2-node,
# 3-node and 2-lane-bus configs with state-hash dedup and sleep-set
# partial-order reduction. Asserts, per state: lattice monotonicity,
# held-queue FIFO + bound, dial-backoff monotonicity; at quiescence:
# digest match on every replica, no stranded rtt stamps, nothing in
# flight. The run must cover >= the recorded model_min_states distinct
# states and finish inside model_budget_seconds (both in
# scripts/jlint/budget.json). Deeper sweep nightly via `-m soak`
# (tests/test_model.py); minimized counterexamples replay from
# tests/model/ in tier-1.
model-smoke:
	JAX_PLATFORMS=cpu $(PY) -m scripts.jmodel --smoke --budget

# nightly CI: the long-running real-process churn/crash drills, including
# the SIGKILL-mid-traffic journal recovery soak, the 16-32 node churn
# soak (tests/test_soak_churn_scale.py — kill/rejoin/partition/heal
# under sustained writes, ends digest-matched with zero whole-state
# dumps), the region-churn soak (tests/test_soak_region_churn.py —
# bridge crash/reboot loops at 3 regions, deterministic succession and
# zero dumps after every handover) and the full fault-injection drill
# matrix (tests/test_drill_matrix.py)
soak:
	$(PY) -m pytest tests/ -q -m soak

bench:
	$(PY) bench.py

# every BASELINE config, one JSON line each (north star first)
bench-all:
	$(PY) bench.py --all

# machine-recorded sweep: writes BENCH_full.json (committed per round so
# every perf claim in README/VERDICT_RESPONSE is auditable)
bench-full:
	$(PY) bench.py --full

# build the native codecs explicitly (they also build lazily on import)
native:
	g++ -O2 -std=c++17 -shared -fPIC -o native/libjylis_native.so native/*.cpp

run:
	$(PY) -m jylis_tpu

# what the driver does: single-chip compile check + virtual multi-chip dryrun
check-graft:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	$(PY) -c "import jax; jax.config.update('jax_platforms','cpu'); \
	import __graft_entry__ as g; fn, a = g.entry(); \
	jax.jit(fn).lower(*a).compile(); g.dryrun_multichip(8); print('OK')"

# ---- release / deployment (reference analog: Dockerfile:24-36 +
# Makefile:31-49 — static binary in a scratch image + nightly upload; the
# rebuild ships a wheel + container image + 3-node compose cluster) ------

# the release artifact: a wheel with the prebuilt native codec bundled.
# The bundled copy is removed WHETHER OR NOT pip succeeds: a leftover
# would shadow fresh native/ builds (the loader prefers the package-local
# .so).
release: native
	rm -rf build dist && mkdir -p dist
	cp native/libjylis_native.so jylis_tpu/native/
	$(PY) -m pip wheel --no-deps --no-build-isolation -w dist .; \
	  rc=$$?; rm -f jylis_tpu/native/libjylis_native.so; exit $$rc
	@ls -l dist/

image:
	docker build -t jylis-tpu .

# full-product smoke: 3-node compose cluster converges all five types
compose-smoke:
	docker compose up -d --build
	$(PY) scripts/smoke3.py --ports 6379,6380,6381; \
	  rc=$$?; docker compose down; exit $$rc

# the same smoke without a container runtime: 3 local node processes
# (what CI runs in this environment)
smoke3:
	$(PY) scripts/smoke3.py --spawn

clean:
	rm -f native/libjylis_native.so jylis_tpu/native/libjylis_native.so \
	  native/libjylis_native_san.so native/libjylis_native_tsan.so
	rm -rf build dist
	find . -name __pycache__ -type d -exec rm -rf {} +
