# jylis-tpu build/test targets (reference analog: the upstream Makefile's
# test/build/debug targets, SURVEY.md section 2.8)

PY ?= python

.PHONY: test bench bench-all native run clean check-graft ci

# what CI runs per commit (.github/workflows/ci.yml): hermetic on any host
ci: native test check-graft

test:
	$(PY) -m pytest tests/ -x -q

bench:
	$(PY) bench.py

# every BASELINE config, one JSON line each (north star first)
bench-all:
	$(PY) bench.py --all

# build the native codecs explicitly (they also build lazily on import)
native:
	g++ -O2 -std=c++17 -shared -fPIC -o native/libjylis_native.so native/*.cpp

run:
	$(PY) -m jylis_tpu

# what the driver does: single-chip compile check + virtual multi-chip dryrun
check-graft:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	$(PY) -c "import jax; jax.config.update('jax_platforms','cpu'); \
	import __graft_entry__ as g; fn, a = g.entry(); \
	jax.jit(fn).lower(*a).compile(); g.dryrun_multichip(8); print('OK')"

clean:
	rm -f native/libjylis_native.so
	find . -name __pycache__ -type d -exec rm -rf {} +
