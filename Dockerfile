# jylis-tpu container image (reference analog: /root/reference/Dockerfile's
# two-stage build — compile in a full toolchain image, ship a minimal
# runtime; the Pony static-binary-in-scratch trick has no Python
# equivalent, so the runtime stage is a slim Python base instead).
#
# CPU image by default (jax[cpu]): a single node, or a docker-compose
# cluster (docker-compose.yml), runs anywhere. For TPU serving, build with
#   --build-arg JAX_EXTRA="jax[tpu] -f https://storage.googleapis.com/jax-releases/libtpu_releases.html"
# on a TPU VM base, or install the image's wheel into your TPU runtime.

FROM python:3.11-slim AS build
RUN apt-get update && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*
WORKDIR /src
COPY native/ native/
# the native codecs (RESP scanner, cluster codec, counter engine): one
# shared object, no Python build step needed
RUN g++ -O2 -std=c++17 -shared -fPIC -o native/libjylis_native.so native/*.cpp

FROM python:3.11-slim
ARG JAX_EXTRA="jax[cpu]"
RUN pip install --no-cache-dir ${JAX_EXTRA} numpy
WORKDIR /app
COPY jylis_tpu/ jylis_tpu/
COPY LICENSE .
COPY --from=build /src/native/libjylis_native.so jylis_tpu/native/
LABEL org.opencontainers.image.title="jylis-tpu" \
      org.opencontainers.image.licenses="MPL-2.0"
ENV JYLIS_NATIVE_SO=/app/jylis_tpu/native/libjylis_native.so
# RESP port (same default as Redis and the reference) + cluster port
EXPOSE 6379 9999
ENTRYPOINT ["python", "-m", "jylis_tpu"]
